"""Reader-writer lock.

Used by the inode tree (coarse tree lock — a deliberate departure from the
reference's 8k-LoC fine-grained per-inode lock scheme,
``file/meta/{InodeLockManager.java:47,InodeTree.java:84}``; see
``master/inode_tree.py`` for the rationale) and by per-block client locks on
the worker (reference: ``worker/block/ClientRWLock.java``).
"""

from __future__ import annotations

import threading


class RWLock:
    """Writer-preferring reader-writer lock, reentrant for readers and for
    the writer (per-thread hold counts make read re-acquisition safe even
    while a writer is queued)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._readers = 0
        self._holds = threading.local()  # this thread's read-hold depth
        self._writer: "threading.Thread | None" = None
        self._writer_depth = 0
        self._waiting_writers = 0
        # Monotonic write-acquisition counter: every mutation of the
        # protected structure requires the write lock, so "version
        # unchanged" == "structure unchanged" (conservative: bumps even
        # for a no-op write section). Read it under the read lock for a
        # coherent snapshot. Used by the master's listing cache.
        self.version = 0

    def _my_holds(self) -> int:
        return getattr(self._holds, "depth", 0)

    # -- read side ----------------------------------------------------------
    def acquire_read(self, timeout: float = None) -> bool:
        me = threading.current_thread()
        with self._cond:
            if self._writer is me:
                self._writer_depth += 1
                return True
            if self._my_holds() > 0:
                # reentrant read: never wait (a queued writer must not
                # deadlock an existing reader re-entering)
                self._holds.depth += 1
                self._readers += 1
                return True
            ok = self._cond.wait_for(
                lambda: self._writer is None and self._waiting_writers == 0,
                timeout)
            if not ok:
                return False
            self._holds.depth = 1
            self._readers += 1
            return True

    def release_read(self) -> None:
        me = threading.current_thread()
        with self._cond:
            if self._writer is me:
                self._writer_depth -= 1
                return
            self._holds.depth = self._my_holds() - 1
            self._readers -= 1
            if self._readers == 0:
                self._cond.notify_all()

    # -- write side ---------------------------------------------------------
    def acquire_write(self, timeout: float = None) -> bool:
        me = threading.current_thread()
        with self._cond:
            if self._writer is me:
                self._writer_depth += 1
                return True
            self._waiting_writers += 1
            try:
                ok = self._cond.wait_for(
                    lambda: self._writer is None and self._readers == 0,
                    timeout)
                if not ok:
                    return False
                self._writer = me
                self._writer_depth = 1
                self.version += 1
                return True
            finally:
                self._waiting_writers -= 1

    def release_write(self) -> None:
        with self._cond:
            self._writer_depth -= 1
            if self._writer_depth == 0:
                self._writer = None
                self._cond.notify_all()

    # -- context managers ---------------------------------------------------
    class _Guard:
        def __init__(self, acquire, release):
            self._acquire, self._release = acquire, release

        def __enter__(self):
            self._acquire()
            return self

        def __exit__(self, *exc):
            self._release()
            return False

    def read_locked(self) -> "_Guard":
        return RWLock._Guard(self.acquire_read, self.release_read)

    def write_locked(self) -> "_Guard":
        return RWLock._Guard(self.acquire_write, self.release_write)
