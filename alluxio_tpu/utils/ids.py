"""Block/file/session id schemes.

Re-design of the reference's id math (``core/common/src/main/java/alluxio/
master/block/BlockId.java`` and ``util/IdUtils.java``): a block id packs a
*container id* (shared by all blocks of one file) with a sequence number;
the file id is the container's max-sequence block id. This keeps
block -> file reverse lookups arithmetic instead of stored.
"""

from __future__ import annotations

import random
import threading
import time

SEQUENCE_BITS = 24
SEQUENCE_MASK = (1 << SEQUENCE_BITS) - 1
MAX_SEQUENCE = SEQUENCE_MASK  # reserved for "the file itself"


def block_id(container_id: int, sequence: int) -> int:
    if not 0 <= sequence <= MAX_SEQUENCE:
        raise ValueError(f"sequence out of range: {sequence}")
    return (container_id << SEQUENCE_BITS) | sequence


def container_id(bid: int) -> int:
    return bid >> SEQUENCE_BITS


def sequence_number(bid: int) -> int:
    return bid & SEQUENCE_MASK


def file_id_from_container(cid: int) -> int:
    return block_id(cid, MAX_SEQUENCE)


def file_id_for_block(bid: int) -> int:
    return file_id_from_container(container_id(bid))


def is_file_id(bid: int) -> bool:
    return sequence_number(bid) == MAX_SEQUENCE


class ContainerIdGenerator:
    """Journaled monotonically-increasing container ids."""

    def __init__(self, next_id: int = 1) -> None:
        self._next = next_id
        self._lock = threading.Lock()

    def next_container_id(self) -> int:
        with self._lock:
            cid = self._next
            self._next += 1
            return cid

    @property
    def peek(self) -> int:
        with self._lock:
            return self._next

    def restore(self, next_id: int) -> None:
        with self._lock:
            self._next = max(self._next, next_id)


_rng = random.Random()
_session_lock = threading.Lock()
_session_counter = 0


def create_session_id() -> int:
    global _session_counter
    with _session_lock:
        _session_counter += 1
        return (int(time.time() * 1000) << 20) | (_session_counter & 0xFFFFF)


def create_worker_id(host: str, port: int) -> int:
    """Random-ish but stable-per-boot worker id."""
    return _rng.getrandbits(62) | 1


def create_mount_id() -> int:
    return _rng.getrandbits(62) | 1


def create_job_id() -> int:
    return _rng.getrandbits(31) | 1
