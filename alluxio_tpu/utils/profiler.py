"""Sampling thread-stack profiler (the microscope's third lens).

Phase events say WHAT the read path was blocked on; this says WHERE the
process was executing while it happened. A daemon thread periodically
snapshots every Python thread's stack via ``sys._current_frames()`` and
merges the samples into flame-graph counts — one ``folded-stack ->
count`` table per process, drained onto the metrics heartbeat and kept
per-source on the master (``/api/v1/master/profile``).

Conf-gated (``atpu.profile.enabled``, default off): when disabled
nothing starts, no thread exists, and the serving paths are
byte-identical to a build without this module. Sampling cost is bounded
by the interval, stack depth and table size
(``atpu.profile.sample.interval.ms`` / ``.stack.depth`` /
``.max.stacks``) — the bench gate ``obs-profile-overhead`` holds the
enabled-path tax under 2%. The dominant cost is NOT the stack walk
(~50us warm): every sampler wake forces a GIL handoff against the
running thread, ~1ms observed on a busy read path, so the default
interval stays coarse (~10Hz) and the walk itself memoizes frame
labels by code object.
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, Optional


class StackSampler:
    """Merged-flame stack sampler for one process."""

    def __init__(self, interval_ms: int = 97, max_stacks: int = 2048,
                 depth: int = 24) -> None:
        self.interval_ms = max(1, int(interval_ms))
        self.max_stacks = max(1, int(max_stacks))
        self.depth = max(1, int(depth))
        self._stacks: Dict[str, int] = {}
        self._samples = 0
        self._dropped = 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        #: code-object -> "file:func" memo. Formatting every frame of
        #: every thread per sample costs ~1ms of GIL in a busy cluster
        #: process (the obs-profile-overhead gate fails on it); a frame
        #: set repeats almost entirely sample-to-sample, so label
        #: construction must be a dict hit, not string work
        self._labels: Dict[object, str] = {}

    # -- lifecycle -----------------------------------------------------------
    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.running:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="atpu-stack-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None

    # -- sampling ------------------------------------------------------------
    def _loop(self) -> None:
        me = threading.get_ident()
        while not self._stop.wait(self.interval_ms / 1000.0):
            self.sample_once(skip_ident=me)

    def sample_once(self, skip_ident: Optional[int] = None) -> None:
        """One merged sample of every live thread's stack (public for
        tests: deterministic sampling without the timing thread)."""
        # sys._current_frames() is a single C-level snapshot — no
        # per-thread locking, and frames are read without running any
        # target-thread code
        frames = sys._current_frames()
        folded = []
        labels = self._labels
        depth = self.depth
        for ident, frame in frames.items():
            if ident == skip_ident:
                continue  # the sampler must not profile itself
            parts = []
            f = frame
            while f is not None and len(parts) < depth:
                code = f.f_code
                lab = labels.get(code)
                if lab is None:
                    if len(labels) >= 8192:
                        labels.clear()  # bound; refills in one sample
                    lab = labels[code] = \
                        f"{code.co_filename.rsplit('/', 1)[-1]}:" \
                        f"{code.co_name}"
                parts.append(lab)
                f = f.f_back
            # root-first, innermost last — the flame-graph convention
            parts.reverse()
            folded.append(";".join(parts))
        with self._lock:
            self._samples += 1
            for key in folded:
                n = self._stacks.get(key)
                if n is None and len(self._stacks) >= self.max_stacks:
                    self._dropped += 1
                    continue
                self._stacks[key] = (n or 0) + 1

    # -- export --------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            return {"samples": self._samples,
                    "interval_ms": self.interval_ms,
                    "dropped": self._dropped,
                    "stacks": dict(self._stacks)}

    def drain(self) -> Optional[dict]:
        """Snapshot-and-reset for the metrics heartbeat: the master
        accumulates the deltas, so a restart of either side never
        double-counts. Returns None when there is nothing to ship."""
        with self._lock:
            if not self._samples:
                return None
            out = {"samples": self._samples,
                   "interval_ms": self.interval_ms,
                   "dropped": self._dropped,
                   "stacks": self._stacks}
            self._stacks = {}
            self._samples = 0
            self._dropped = 0
        return out


_PROFILER = StackSampler()


def profiler() -> StackSampler:
    return _PROFILER


def apply_profile_conf(conf) -> None:
    """Apply the ``atpu.profile.*`` keys to the process sampler and
    start/stop it to match ``atpu.profile.enabled`` (mirrors
    ``tracing.apply_trace_conf``)."""
    from alluxio_tpu.conf import Keys

    p = _PROFILER
    p.interval_ms = max(1, conf.get_int(Keys.PROFILE_SAMPLE_INTERVAL_MS))
    p.max_stacks = max(1, conf.get_int(Keys.PROFILE_MAX_STACKS))
    p.depth = max(1, conf.get_int(Keys.PROFILE_STACK_DEPTH))
    if conf.get_bool(Keys.PROFILE_ENABLED):
        p.start()
    else:
        p.stop()


def merge_flames(base: dict, delta: dict) -> dict:
    """Accumulate one drained flame delta into a running total (the
    master's per-source store uses this; also handy for tests)."""
    out = dict(base) if base else {"samples": 0, "dropped": 0,
                                   "stacks": {}}
    out["samples"] = int(out.get("samples", 0)) + \
        int(delta.get("samples", 0))
    out["dropped"] = int(out.get("dropped", 0)) + \
        int(delta.get("dropped", 0))
    if "interval_ms" in delta:
        out["interval_ms"] = delta["interval_ms"]
    stacks = dict(out.get("stacks") or {})
    for key, n in (delta.get("stacks") or {}).items():
        stacks[key] = stacks.get(key, 0) + int(n)
    out["stacks"] = stacks
    return out
