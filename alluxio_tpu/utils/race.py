"""Race/deadlock detection tooling — the TSAN analogue for this codebase.

Re-design of the reference's sanitizer CI surface (SURVEY §5.2: TSAN
builds + deadlock-prone lock-order tests): Python's GIL removes data
races on plain attributes, so the remaining deadlock class worth
machine-checking is **lock-order inversion** (thread 1 holds A wants B,
thread 2 holds B wants A). ``LockOrderAuditor`` instruments chosen locks
and records the held-set every time another lock is acquired; any pair
observed in both orders — on any schedule, even one that didn't deadlock
this run — is reported with both acquisition stacks. ``Watchdog`` is the
companion hang-breaker: it dumps every thread's stack and aborts the
test instead of letting CI time out silently.
"""

from __future__ import annotations

import faulthandler
import sys
import threading
import traceback
from typing import Dict, List, Optional, Set, Tuple


class _LockProxy:
    """Wraps a Lock/RLock/RWLock-ish object, reporting to the auditor."""

    def __init__(self, inner, name: str,
                 auditor: "LockOrderAuditor") -> None:
        self._inner = inner
        self._name = name
        self._auditor = auditor

    # context-manager protocol (the common usage in this codebase)
    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False

    def acquire(self, *a, **kw):
        blocking = bool(a[0] if a else kw.get("blocking", True))
        timeout = a[1] if len(a) > 1 else kw.get("timeout", -1)
        # a BOUNDED acquire (trylock or timed backoff) cannot deadlock:
        # its edge records on success only, like TSAN's try-lock rule
        bounded = (not blocking) or (
            timeout is not None and timeout >= 0)
        self._auditor._before_acquire(self._name, blocking=not bounded)
        got = self._inner.acquire(*a, **kw)
        if got:
            self._auditor._acquired(self._name, record=bounded)
        else:
            self._auditor._abandoned(self._name)
        return got

    def release(self):
        self._auditor._released(self._name)
        return self._inner.release()

    # RWLock surface (utils/locks.py): both sides audit as one node —
    # order inversions matter regardless of read/write mode
    def _rw_acquire(self, fn, *a, **kw):
        timeout = a[0] if a else kw.get("timeout")
        bounded = timeout is not None and timeout >= 0
        self._auditor._before_acquire(self._name, blocking=not bounded)
        got = fn(*a, **kw)
        if got:
            self._auditor._acquired(self._name, record=bounded)
        else:
            self._auditor._abandoned(self._name)
        return got

    def acquire_read(self, *a, **kw):
        return self._rw_acquire(self._inner.acquire_read, *a, **kw)

    def release_read(self):
        self._auditor._released(self._name)
        return self._inner.release_read()

    def acquire_write(self, *a, **kw):
        return self._rw_acquire(self._inner.acquire_write, *a, **kw)

    def release_write(self):
        self._auditor._released(self._name)
        return self._inner.release_write()

    def read_locked(self):
        from alluxio_tpu.utils.locks import RWLock

        return RWLock._Guard(self.acquire_read, self.release_read)

    def write_locked(self):
        from alluxio_tpu.utils.locks import RWLock

        return RWLock._Guard(self.acquire_write, self.release_write)

    def __getattr__(self, item):
        return getattr(self._inner, item)


class LockOrderAuditor:
    """Records lock-acquisition ORDER edges across threads.

    An edge ``A -> B`` means "some thread held A while acquiring B".
    Observing both ``A -> B`` and ``B -> A`` (from any threads, any
    time) proves a schedule exists that deadlocks — the same invariant
    TSAN's deadlock detector checks.
    """

    def __init__(self) -> None:
        self._held = threading.local()
        #: (held, acquiring) -> formatted stack of first observation
        self.edges: Dict[Tuple[str, str], str] = {}
        self._edges_lock = threading.Lock()

    # -- instrumentation -----------------------------------------------------
    def wrap(self, lock, name: str) -> _LockProxy:
        return _LockProxy(lock, name, self)

    def instrument_attr(self, obj, attr: str, name: str) -> None:
        """Replace ``obj.<attr>`` with an audited proxy in place."""
        setattr(obj, attr, self.wrap(getattr(obj, attr), name))

    def _stack(self) -> List[str]:
        return getattr(self._held, "stack", None) or []

    def _record_edges(self, name: str) -> None:
        for held in self._stack():
            if held == name:
                continue  # reentrant
            key = (held, name)
            if key not in self.edges:
                with self._edges_lock:
                    self.edges.setdefault(
                        key, "".join(traceback.format_stack(limit=12)))

    def _before_acquire(self, name: str, blocking: bool = True) -> None:
        # BLOCKING acquires record their edge up front — in an actual
        # deadlock neither thread returns from acquire, and recording
        # only on success would make the auditor blind in exactly the
        # run that hangs. Non-blocking try-locks record on success only
        # (hold-A-trylock-B-backoff cannot deadlock; TSAN exempts
        # try-lock edges the same way).
        if blocking:
            self._record_edges(name)

    def _acquired(self, name: str, *, record: bool = False) -> None:
        if record:
            self._record_edges(name)
        stack = self._stack()
        stack.append(name)
        self._held.stack = stack

    def _abandoned(self, name: str) -> None:
        pass  # non-blocking acquire failed: nothing held

    def _released(self, name: str) -> None:
        stack = self._stack()
        if name in stack:
            stack.reverse()
            stack.remove(name)
            stack.reverse()

    # -- analysis ------------------------------------------------------------
    def inversions(self) -> List[Tuple[str, str]]:
        """Lock pairs observed in BOTH orders (a potential deadlock)."""
        seen: Set[Tuple[str, str]] = set(self.edges)
        out = []
        for a, b in seen:
            if (b, a) in seen and a < b:
                out.append((a, b))
        return sorted(out)

    def report(self) -> str:
        lines = []
        for a, b in self.inversions():
            lines.append(f"lock-order inversion: {a} <-> {b}")
            lines.append(f"-- {a} held while acquiring {b}:")
            lines.append(self.edges[(a, b)])
            lines.append(f"-- {b} held while acquiring {a}:")
            lines.append(self.edges[(b, a)])
        return "\n".join(lines)

    def assert_clean(self) -> None:
        inv = self.inversions()
        if inv:
            raise AssertionError(
                f"lock-order inversions detected: {inv}\n{self.report()}")


class Watchdog:
    """Hang-breaker: dump all thread stacks and raise after a deadline.

    Usage::

        with Watchdog(30):
            run_concurrent_workload()
    """

    def __init__(self, timeout_s: float,
                 stream=None) -> None:
        self._timeout = timeout_s
        self._stream = stream or sys.stderr
        self._timer: Optional[threading.Timer] = None
        self.fired = False

    def _fire(self) -> None:
        self.fired = True
        self._stream.write(
            f"\n=== Watchdog: still running after {self._timeout}s — "
            f"thread dump ===\n")
        try:
            faulthandler.dump_traceback(file=self._stream)
        except Exception:  # noqa: BLE001
            # stream without a real fileno (StringIO): python fallback
            for tid, frame in sys._current_frames().items():
                self._stream.write(f"\n--- thread {tid} ---\n")
                self._stream.write(
                    "".join(traceback.format_stack(frame)))
        self._stream.flush()

    def __enter__(self) -> "Watchdog":
        self._timer = threading.Timer(self._timeout, self._fire)
        self._timer.daemon = True
        self._timer.start()
        return self

    def __exit__(self, *exc) -> bool:
        if self._timer is not None:
            self._timer.cancel()
        if self.fired and exc[0] is None:
            raise TimeoutError(
                f"watchdog fired after {self._timeout}s (stacks dumped "
                f"to stderr)")
        return False
