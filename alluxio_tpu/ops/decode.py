"""Jitted record decode ops: raw cached bytes -> model-ready batches.

The data-plane's on-device tail: everything here stays inside ``jit`` so
XLA fuses the cast/normalize into the first matmul's input pipeline (no
separate HBM round-trip for elementwise work — the guide's rule of keeping
HBM-bound elementwise ops fused).

Record format for the image path mirrors fixed-size TFRecord-style
samples: ``label(4B little-endian) || H*W*C uint8 pixels``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


@functools.partial(jax.jit, static_argnames=("height", "width", "channels"))
def decode_image_records(records: jax.Array, *, height: int, width: int,
                         channels: int = 3):
    """(batch, record_bytes) uint8 -> ((batch,H,W,C) bf16 normalized, labels).

    Cast + scale + normalize fuse into one pass; output is bf16 for the MXU.
    """
    labels = (records[:, 0].astype(jnp.int32)
              | (records[:, 1].astype(jnp.int32) << 8)
              | (records[:, 2].astype(jnp.int32) << 16)
              | (records[:, 3].astype(jnp.int32) << 24))
    pixels = records[:, 4:4 + height * width * channels]
    imgs = pixels.reshape(-1, height, width, channels).astype(jnp.float32)
    imgs = imgs / 255.0
    mean = jnp.asarray(IMAGENET_MEAN, dtype=jnp.float32)
    std = jnp.asarray(IMAGENET_STD, dtype=jnp.float32)
    imgs = (imgs - mean) / std
    return imgs.astype(jnp.bfloat16), labels


def image_record_bytes(height: int, width: int, channels: int = 3) -> int:
    return 4 + height * width * channels


def encode_image_records(images, labels) -> bytes:
    """Host-side encoder (writers/tests): the inverse of
    ``decode_image_records``. numpy-only; never inside jit."""
    import numpy as np

    images = np.asarray(images, dtype=np.uint8)
    labels = np.asarray(labels, dtype=np.int32)
    n = images.shape[0]
    flat = images.reshape(n, -1)
    out = np.empty((n, 4 + flat.shape[1]), dtype=np.uint8)
    out[:, :4] = labels.astype("<i4").view(np.uint8).reshape(n, 4)
    out[:, 4:] = flat
    return out.tobytes()


@jax.jit
def sum_bytes(block: jax.Array):
    """Forces a full device-side read of a cached block (benchmarks use
    this to measure HBM-tier serving bandwidth)."""
    return jnp.sum(block.astype(jnp.uint32))
