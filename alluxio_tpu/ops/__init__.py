"""On-device data ops (decode, batch assembly, kernels)."""

from alluxio_tpu.ops.decode import (  # noqa: F401
    decode_image_records, encode_image_records, image_record_bytes, sum_bytes,
)
