"""Pallas TPU kernel: streaming scaled-sum over HBM-resident blocks.

The hot op of the warm-read path (bench config #1 and any
``device-side scan`` consumer): read every cached byte once, multiply
by a scalar, reduce. XLA's fused reduce already runs near HBM peak;
this kernel exists to (a) own the schedule explicitly — a gridded
``BlockSpec`` pipeline double-buffers the HBM->VMEM DMAs against the
VPU reduce with no fusion-heuristic dependence — and (b) serve as the
repo's reference pallas pattern (guide: ``pallas_guide.md`` grid/
BlockSpec pipelining).

Falls back cleanly: callers use ``available()`` and keep the jnp path
(e.g. ``bench.py``) when pallas/TPU is absent.
"""

from __future__ import annotations

_LANES = 1024  # 8x128 VPU tile multiples
_ROWS = 512    # rows per grid step: 512x1024 int32 = 2 MiB VMEM/block
# Candidate block heights for calibration: at 819 GB/s a 2 MiB block is
# only ~2.6 us of DMA, so fixed per-grid-step cost can be a few percent;
# taller blocks amortize it (16 MiB = ~20 us/step, 2x16 MiB double
# buffer = 32 MiB of ~128 MiB VMEM). bench.py times each and keeps the
# winner rather than guessing the sweet spot for this chip stepping.
CALIBRATION_ROWS = (512, 1024, 2048, 4096)


def available() -> bool:
    try:
        import jax
        from jax.experimental import pallas as pl  # noqa: F401

        return jax.devices()[0].platform == "tpu"
    except Exception:  # noqa: BLE001
        return False


def _kernel(x_ref, s_ref, o_ref):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    @pl.when(pl.program_id(0) == 0)
    def _init():
        o_ref[0, 0] = jnp.int32(0)

    # VPU multiply-reduce over this block; accumulation is safe because
    # the TPU grid executes sequentially
    o_ref[0, 0] += jnp.sum(x_ref[:] * s_ref[0, 0])


def scaled_sum(x, scale, *, rows: int = _ROWS, interpret: bool = False):
    """``sum(x * scale)`` for int32 ``x`` of size divisible by
    ``rows * _LANES`` (use ``pad_to_kernel_shape`` otherwise — zeros
    are reduction-neutral). Trace-time shapes, so calling this inside
    the consumer's ``jit`` compiles it once; no module-level jax import
    (``available()`` must stay checkable on jax-less hosts)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if x.size % (rows * _LANES):
        raise ValueError(
            f"input size {x.size} is not a multiple of "
            f"{rows * _LANES}; pad with pad_to_kernel_shape() — "
            f"flooring would silently drop the tail from the reduction")
    flat = x.reshape(-1, _LANES)
    tiles = flat.shape[0] // rows
    grid_spec = pl.GridSpec(
        grid=(tiles,),
        in_specs=[
            pl.BlockSpec((rows, _LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda i: (0, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda i: (0, 0),
                               memory_space=pltpu.SMEM),
    )
    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.int32),
        grid_spec=grid_spec,
        interpret=interpret,
    )(flat, scale.reshape(1, 1).astype(jnp.int32))
    return out[0, 0]


def pad_to_kernel_shape(arr, *, rows: int = _ROWS):
    """Zero-pad a flat int32 array up to the kernel's block multiple."""
    import jax.numpy as jnp

    block = rows * _LANES
    n = arr.size
    rem = (-n) % block
    if rem:
        arr = jnp.concatenate(
            [arr.reshape(-1), jnp.zeros((rem,), dtype=arr.dtype)])
    return arr.reshape(-1, _LANES)
