"""Shell command framework.

Re-design of ``shell/src/main/java/alluxio/cli/{Command,AbstractShell}.java``:
a command registry per shell, argparse-based per-command options, and a
lazily-built client context so `help` works without a running cluster.
"""

from __future__ import annotations

import argparse
import fnmatch
import sys
from typing import Callable, Dict, List, Optional, TextIO

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.utils.exceptions import AlluxioTpuError
from alluxio_tpu.utils.wire import FileInfo


class CommandError(Exception):
    """User-facing command failure (maps to exit code 1, message on stderr)."""


class ShellContext:
    """Lazily-constructed clients shared by every command in one invocation."""

    def __init__(self, conf: Optional[Configuration] = None,
                 out: Optional[TextIO] = None,
                 err: Optional[TextIO] = None) -> None:
        self.conf = conf or Configuration()
        # Late-bound: a default-constructed context must follow RUNTIME
        # sys.stdout/sys.stderr swaps (capsys, supervisors), not whatever
        # the streams were at import time.
        self._out = out
        self._err = err
        self._fs = None
        self._fs_client = None
        self._block_client = None
        self._meta_client = None
        self._job_client = None
        self._table_client = None

    @property
    def out(self) -> TextIO:
        return self._out if self._out is not None else sys.stdout

    @out.setter
    def out(self, stream: Optional[TextIO]) -> None:
        self._out = stream

    @property
    def err(self) -> TextIO:
        return self._err if self._err is not None else sys.stderr

    @err.setter
    def err(self, stream: Optional[TextIO]) -> None:
        self._err = stream

    @property
    def master_address(self) -> str:
        addresses = self.conf.get(Keys.MASTER_RPC_ADDRESSES)
        if addresses:
            return str(addresses)
        return (f"{self.conf.get(Keys.MASTER_HOSTNAME)}:"
                f"{self.conf.get_int(Keys.MASTER_RPC_PORT)}")

    @property
    def job_master_address(self) -> str:
        return (f"{self.conf.get(Keys.JOB_MASTER_HOSTNAME)}:"
                f"{self.conf.get_int(Keys.JOB_MASTER_RPC_PORT)}")

    def fs(self):
        if self._fs is None:
            from alluxio_tpu.client.file_system import FileSystem

            self._fs = FileSystem(self.master_address, conf=self.conf)
        return self._fs

    def fs_client(self):
        if self._fs_client is None:
            from alluxio_tpu.rpc.clients import FsMasterClient

            self._fs_client = FsMasterClient(self.master_address,
                                             conf=self.conf)
        return self._fs_client

    def block_client(self):
        if self._block_client is None:
            from alluxio_tpu.rpc.clients import BlockMasterClient

            self._block_client = BlockMasterClient(self.master_address,
                                                   conf=self.conf)
        return self._block_client

    def meta_client(self):
        if self._meta_client is None:
            from alluxio_tpu.rpc.clients import MetaMasterClient

            self._meta_client = MetaMasterClient(self.master_address,
                                                 conf=self.conf)
        return self._meta_client

    def job_client(self):
        if self._job_client is None:
            from alluxio_tpu.rpc.job_service import JobMasterClient

            self._job_client = JobMasterClient(self.job_master_address,
                                               conf=self.conf)
        return self._job_client

    def table_client(self):
        if self._table_client is None:
            from alluxio_tpu.rpc.table_service import TableMasterClient

            self._table_client = TableMasterClient(self.master_address,
                                                   conf=self.conf)
        return self._table_client

    def close(self) -> None:
        if self._fs is not None:
            self._fs.close()

    # -- output helpers ------------------------------------------------------
    def print(self, *args) -> None:
        print(*args, file=self.out)

    def eprint(self, *args) -> None:
        print(*args, file=self.err)


class Command:
    """One shell command. Subclasses set ``name``/``usage``/``description``,
    add options in ``configure(parser)`` and implement ``run(args, ctx)``."""

    name: str = ""
    usage: str = ""
    description: str = ""

    def configure(self, parser: argparse.ArgumentParser) -> None:  # noqa: B027
        pass

    def run(self, args: argparse.Namespace, ctx: ShellContext) -> int:
        raise NotImplementedError

    def make_parser(self, prog_prefix: str) -> argparse.ArgumentParser:
        # resolve conflicts so command flags like ls -h (human sizes) win
        # over argparse's auto -h/--help (--help still works)
        p = argparse.ArgumentParser(
            prog=f"{prog_prefix} {self.name}", description=self.description,
            conflict_handler="resolve")
        self.configure(p)
        return p


class Shell:
    """A named shell = registry of commands + a dispatch loop
    (reference: ``AbstractShell.run``)."""

    def __init__(self, name: str, description: str = "") -> None:
        self.name = name
        self.description = description
        self.commands: Dict[str, Command] = {}

    def register(self, cmd_cls: type) -> type:
        cmd = cmd_cls()
        self.commands[cmd.name] = cmd
        return cmd_cls

    def print_usage(self, ctx: ShellContext) -> None:
        ctx.print(f"Usage: alluxio-tpu {self.name} [generic options] "
                  f"<command> [command options]")
        ctx.print(f"\n{self.description}\nCommands:")
        for name in sorted(self.commands):
            c = self.commands[name]
            ctx.print(f"  {name:<22s} {c.description}")

    def run(self, argv: List[str], ctx: Optional[ShellContext] = None) -> int:
        ctx = ctx or ShellContext()
        if not argv or argv[0] in ("help", "-h", "--help"):
            if len(argv) > 1 and argv[1] in self.commands:
                self.commands[argv[1]].make_parser(
                    f"alluxio-tpu {self.name}").print_help(ctx.out)
                return 0
            self.print_usage(ctx)
            return 0
        name, rest = argv[0], argv[1:]
        cmd = self.commands.get(name)
        if cmd is None:
            ctx.eprint(f"{name} is not a valid command.")
            self.print_usage(ctx)
            return 1
        parser = cmd.make_parser(f"alluxio-tpu {self.name}")
        try:
            args = parser.parse_args(rest)
        except SystemExit as e:
            return int(e.code or 0)
        try:
            return cmd.run(args, ctx) or 0
        except CommandError as e:
            ctx.eprint(str(e))
            return 1
        except AlluxioTpuError as e:
            ctx.eprint(f"{type(e).__name__}: {e}")
            return 1
        finally:
            ctx.close()


# -- shared formatting helpers (reference: FileSystemShellUtils) -------------

def human_size(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB", "PB"):
        if abs(n) < 1024 or unit == "PB":
            return f"{n:.2f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.2f}PB"


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values, width: int = 60) -> str:
    """ASCII(ish) sparkline of a numeric series, last ``width`` points
    (`fsadmin report history`).  Flat series render as a low bar, not a
    divide-by-zero."""
    vals = [float(v) for v in values][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK_BLOCKS[0] * len(vals)
    top = len(_SPARK_BLOCKS) - 1
    return "".join(_SPARK_BLOCKS[int(round((v - lo) / span * top))]
                   for v in vals)


def mode_string(info: FileInfo) -> str:
    kind = "d" if info.folder else "-"
    bits = ""
    for shift in (6, 3, 0):
        trio = (info.mode >> shift) & 7
        bits += ("r" if trio & 4 else "-") + ("w" if trio & 2 else "-") + \
            ("x" if trio & 1 else "-")
    return kind + bits


def format_ls_line(info: FileInfo, human: bool = False) -> str:
    import datetime

    size = human_size(info.length) if human else str(info.length)
    when = datetime.datetime.fromtimestamp(
        info.last_modification_time_ms / 1000.0
    ).strftime("%m-%d-%Y %H:%M:%S")
    state = info.persistence_state
    return (f"{mode_string(info)} {info.owner or '-':<10s} "
            f"{info.group or '-':<10s} {size:>12s} {state:<14s} {when} "
            f"{'DIR' if info.folder else f'{info.in_memory_percentage}%':>4s} "
            f"{info.path}")


def expand_globs(fs, path: str) -> List[str]:
    """Expand a trailing-component glob (``/a/b*``) against the namespace
    (reference: FileSystemShellUtils.getAlluxioURIs)."""
    if not any(ch in path for ch in "*?[]"):
        return [path]
    from alluxio_tpu.utils.uri import AlluxioURI

    uri = AlluxioURI(path)
    parent = uri.parent()
    if parent is None:
        return [path]
    matches = [i.path for i in fs.list_status(parent.path)
               if fnmatch.fnmatch(i.path.rsplit("/", 1)[-1], uri.name)]
    if not matches:
        raise CommandError(f"{path} does not match any file or directory")
    return sorted(matches)
