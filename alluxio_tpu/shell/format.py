"""``alluxio-tpu format`` — wipe journal and worker storage dirs.

Re-design of ``core/server/common/src/main/java/alluxio/cli/Format.java:45,80``:
``format master`` clears the journal folder; ``format worker`` clears every
configured tier directory. Refuses to touch paths outside the configured
locations.
"""

from __future__ import annotations

import os
import shutil
import sys

from alluxio_tpu.conf import Configuration, Keys, Templates


def _wipe_dir(path: str, out) -> None:
    if os.path.isdir(path):
        for name in os.listdir(path):
            full = os.path.join(path, name)
            if os.path.isdir(full):
                shutil.rmtree(full, ignore_errors=True)
            else:
                try:
                    os.unlink(full)
                except OSError:
                    pass
        print(f"Formatting {path}", file=out)
    else:
        os.makedirs(path, exist_ok=True)
        print(f"Created {path}", file=out)


def format_master(conf: Configuration, out=None) -> None:
    # out=None late-binds: print(file=None) writes to the CURRENT sys.stdout.
    _wipe_dir(conf.get(Keys.MASTER_JOURNAL_FOLDER), out)


def format_worker(conf: Configuration, out=None) -> None:
    levels = conf.get_int(Keys.WORKER_TIERED_STORE_LEVELS)
    for lvl in range(levels):
        for p in conf.get_list(Templates.WORKER_TIER_DIRS_PATH.format(lvl)):
            _wipe_dir(p, out)
    data_folder = conf.get(Keys.WORKER_DATA_FOLDER)
    if data_folder:
        _wipe_dir(data_folder, out)


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    target = argv[0] if argv else "all"
    conf = Configuration()
    if target in ("master", "all"):
        format_master(conf)
    if target in ("worker", "all"):
        format_worker(conf)
    if target not in ("master", "worker", "all"):
        print(f"Usage: alluxio-tpu format [master|worker|all]",
              file=sys.stderr)
        return 1
    return 0
