"""journalCrashTest: hammer the master with metadata ops while
repeatedly SIGKILLing it, then verify every acknowledged op survived
journal replay.

Env-adapted analogue of the reference's ``shell/.../cli/
JournalCrashTest.java:43``: client threads run CREATE_FILE /
CREATE_DELETE_FILE / CREATE_RENAME_FILE loops counting acknowledged
successes; a supervisor bounds each master's lifetime (``--max-alive``)
by hard-killing and restarting it until ``--total-time`` elapses; the
final check reconnects and asserts the exact acknowledged state is
reproduced by replay (exit 0/1). Reconciliation on retry mirrors the
journal's at-least-once reality: an op that raised after the crash may
still have committed (ack lost), so a retry that finds the op's
outcome already in place counts it succeeded rather than spinning on
AlreadyExists forever.
"""

from __future__ import annotations

import itertools
import shutil
import sys
import tempfile
import threading
import time
from typing import List, Optional

from alluxio_tpu.utils.exceptions import (
    FileAlreadyExistsError, FileDoesNotExistError, NotFoundError,
)

_GONE = (FileDoesNotExistError, NotFoundError)

CREATE = "create"
CREATE_DELETE = "create_delete"
CREATE_RENAME = "create_rename"


class _OpThread(threading.Thread):
    def __init__(self, cluster, kind: str, workdir: str,
                 op_sleep_s: float = 0.02) -> None:
        super().__init__(name=f"crash-{kind}", daemon=True)
        self._cluster = cluster
        self.kind = kind
        self.workdir = workdir
        self.success = 0
        self._sleep = op_sleep_s
        self._halt = threading.Event()

    def stop(self) -> None:
        self._halt.set()

    def run(self) -> None:  # noqa: C901 — one small op state machine
        fs = self._cluster.file_system()
        try:
            while not self._halt.is_set():
                path = f"{self.workdir}{self.success}"
                try:
                    if self.kind == CREATE:
                        try:
                            fs.write_all(path, b"")
                        except FileAlreadyExistsError:
                            pass  # committed before a lost ack
                    elif self.kind == CREATE_DELETE:
                        try:
                            fs.write_all(path, b"")
                        except FileAlreadyExistsError:
                            pass
                        try:
                            fs.delete(path)
                        except _GONE:
                            pass  # delete committed, ack lost
                    elif self.kind == CREATE_RENAME:
                        try:
                            fs.write_all(path, b"")
                        except FileAlreadyExistsError:
                            pass
                        try:
                            fs.rename(path, path + "-rename")
                        except _GONE + (FileAlreadyExistsError,):
                            # src gone or dst taken: committed with a
                            # lost ack IF the renamed file is there —
                            # e.g. a crash-retry recreated src, then
                            # rename found dst from the committed op
                            if not fs.exists(path + "-rename"):
                                raise
                except Exception:  # noqa: BLE001 — master mid-crash;
                    time.sleep(0.2)  # keep requesting (reference)
                    continue
                self.success += 1
                time.sleep(self._sleep)
        finally:
            try:
                fs.close()
            except Exception:  # noqa: BLE001
                pass


def _verify(fs, threads: List[_OpThread], log) -> bool:
    ok = True
    for t in threads:
        log(f"expect: kind={t.kind} workdir={t.workdir} "
            f"acked={t.success}")
        for s in range(t.success):
            path = f"{t.workdir}{s}"
            if t.kind == CREATE and not fs.exists(path):
                log(f"FAILED: {path} missing after replay")
                ok = False
            elif t.kind == CREATE_DELETE and fs.exists(path):
                log(f"FAILED: {path} still exists after replay")
                ok = False
            elif t.kind == CREATE_RENAME and \
                    not fs.exists(path + "-rename"):
                log(f"FAILED: {path}-rename missing after replay")
                ok = False
    return ok


def run_crash_test(*, total_time_s: float = 20.0,
                   max_alive_s: float = 5.0,
                   creates: int = 1, create_deletes: int = 1,
                   create_renames: int = 1,
                   journal_type: str = "LOCAL", num_masters: int = 1,
                   base_dir: Optional[str] = None,
                   test_dir: str = "/crash-test",
                   kill: str = "all",
                   log=None) -> bool:
    """``kill``: "all" SIGKILLs every master each cycle (cold restart +
    replay — the reference tool's shape); "leader" kills only the
    serving primary, so a multi-master quorum must keep accepting
    writes through failover while the victim restarts and catches up."""
    from alluxio_tpu.minicluster import MultiProcessCluster

    if kill not in ("all", "leader"):
        raise ValueError(f"kill must be 'all' or 'leader', got {kill!r}")
    log = log or (lambda *a: print(*a, file=sys.stderr))
    base = base_dir or tempfile.mkdtemp(prefix="atpu_crash_")
    own_base = base_dir is None
    try:
        with MultiProcessCluster(base, num_masters=num_masters,
                                 num_workers=0,
                                 journal_type=journal_type) as cluster:
            fs = cluster.file_system()
            fs.create_directory(test_dir, recursive=True,
                                allow_exists=True)
            threads: List[_OpThread] = []
            counter = itertools.count()
            for kind, n in ((CREATE, creates),
                            (CREATE_DELETE, create_deletes),
                            (CREATE_RENAME, create_renames)):
                for _ in range(n):
                    t = _OpThread(cluster, kind,
                                  f"{test_dir}/{kind}-{next(counter)}-")
                    threads.append(t)
                    t.start()
            deadline = time.monotonic() + total_time_s
            crashes = 0
            while time.monotonic() < deadline:
                time.sleep(min(max_alive_s,
                               max(0.0, deadline - time.monotonic())))
                if time.monotonic() >= deadline:
                    break
                if kill == "leader":
                    li = cluster.primary_index()
                    cluster.masters[li].kill()
                    crashes += 1
                    log(f"crash #{crashes}: leader m{li} SIGKILLed, "
                        "restarting it (quorum keeps serving)")
                    cluster.start_master(li)
                else:
                    # hard-kill every living master (LOCAL: the one
                    # primary; EMBEDDED: leader + followers too)
                    for i, m in enumerate(cluster.masters):
                        if m.alive:
                            m.kill()
                    crashes += 1
                    log(f"crash #{crashes}: all masters SIGKILLed, "
                        "restarting")
                    for i in range(len(cluster.masters)):
                        cluster.start_master(i)
                cluster.wait_for_primary()
            for t in threads:
                t.stop()
            for t in threads:
                t.join(timeout=30)
            log(f"ran {crashes} crash cycle(s); "
                f"acks: {[t.success for t in threads]}")
            # final replay check on a fresh client against the
            # post-crash primary
            cluster.wait_for_primary()
            fs2 = cluster.file_system()
            ok = _verify(fs2, threads, log)
            fs2.close()
            fs.close()
            if not any(t.success for t in threads):
                log("FAILED: no operation was ever acknowledged — "
                    "the test exercised nothing")
                ok = False
            return ok
    finally:
        if own_base:
            shutil.rmtree(base, ignore_errors=True)


def main(argv=None, out=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="alluxio-tpu journalCrashTest")
    ap.add_argument("--total-time", type=float, default=20.0,
                    help="seconds to run the whole test")
    ap.add_argument("--max-alive", type=float, default=5.0,
                    help="max seconds any master stays alive")
    ap.add_argument("--creates", type=int, default=1)
    ap.add_argument("--create-deletes", type=int, default=1)
    ap.add_argument("--create-renames", type=int, default=1)
    ap.add_argument("--journal", default="LOCAL",
                    choices=["LOCAL", "EMBEDDED"])
    ap.add_argument("--masters", type=int, default=1)
    ap.add_argument("--kill", default="all", choices=["all", "leader"])
    ap.add_argument("--dir", default="/crash-test")
    args = ap.parse_args(argv)
    stream = out or sys.stderr

    def log(*a):
        print(*a, file=stream, flush=True)

    ok = run_crash_test(
        total_time_s=args.total_time, max_alive_s=args.max_alive,
        creates=args.creates, create_deletes=args.create_deletes,
        create_renames=args.create_renames, journal_type=args.journal,
        num_masters=args.masters, test_dir=args.dir, kill=args.kill,
        log=log)
    log("journalCrashTest: " + ("PASSED" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
