"""FileSystemAdminShell: ``alluxio-tpu fsadmin <command>``.

Re-design of ``shell/src/main/java/alluxio/cli/fsadmin/
{FileSystemAdminShell.java,command/*,report/*,doctor/*}``: cluster report,
doctor checks, journal checkpoint, and UFS listing for operators.
"""

from __future__ import annotations

import sys
import time

from alluxio_tpu.conf import Source
from alluxio_tpu.shell.command import (
    Command, CommandError, Shell, human_size, sparkline,
)

ADMIN_SHELL = Shell("fsadmin", "Administer the alluxio-tpu cluster.")


@ADMIN_SHELL.register
class ReportCommand(Command):
    name = "report"
    description = ("Report cluster summary|capacity|ufs|metrics|"
                   "jobservice|stall|readpath|history|health|qos|"
                   "masters.")

    def configure(self, p):
        p.add_argument("category", nargs="?", default="summary",
                       choices=["summary", "capacity", "ufs", "metrics",
                                "jobservice", "stall", "readpath",
                                "history", "health", "qos", "masters",
                                "metastore"])
        p.add_argument("metric", nargs="?", default="",
                       help="history: metric name (omit to list "
                            "recorded names)")
        p.add_argument("--source", default="",
                       help="history: only this reporting source")
        p.add_argument("--resolution", default="raw",
                       choices=["raw", "1m", "10m"],
                       help="history: sample tier to print")
        p.add_argument("--rate", action="store_true",
                       help="history: derive a per-second rate "
                            "(counters)")

    def run(self, args, ctx):
        if args.category == "history":
            return self._history(ctx, args)
        # history-only arguments must not be silently swallowed for the
        # other categories (`report metrics Worker.X` is a usage error,
        # not the full unfiltered dump)
        extras = [what for what, given in (
            (f"metric '{args.metric}'", args.metric),
            ("--source", args.source),
            ("--resolution", args.resolution != "raw"),
            ("--rate", args.rate)) if given]
        if extras:
            ctx.eprint(f"report {args.category} does not take "
                       f"{', '.join(extras)} (history-only)")
            return 2
        if args.category == "health":
            return self._health(ctx, args)
        return getattr(self, f"_{args.category}")(ctx)

    def _summary(self, ctx):
        info = ctx.meta_client().get_master_info()
        cap = ctx.block_client().get_capacity()
        workers = ctx.block_client().get_worker_infos(
            include_quarantined=True)
        started = time.strftime(
            "%m-%d-%Y %H:%M:%S",
            time.localtime(info.get("start_time_ms", 0) / 1000))
        uptime_s = max(0, time.time() - info.get("start_time_ms", 0) / 1000)
        ctx.print("Alluxio-TPU cluster summary:")
        ctx.print(f"    Master Address: {ctx.master_address}")
        ctx.print(f"    Cluster Id: {info.get('cluster_id', '')}")
        ctx.print(f"    Started: {started}")
        ctx.print(f"    Uptime: {int(uptime_s)}s")
        ctx.print(f"    Safe Mode: {info.get('safe_mode', False)}")
        quarantined = sum(1 for w in workers if w.state == "QUARANTINED")
        ctx.print(f"    Live Workers: {len(workers)}"
                  + (f" ({quarantined} quarantined)"
                     if quarantined else ""))
        total = sum(cap["capacity"].values())
        used = sum(cap["used"].values())
        ctx.print(f"    Total Capacity: {human_size(total)}")
        for tier, n in sorted(cap["capacity"].items()):
            ctx.print(f"        Tier: {tier}  Size: {human_size(n)}")
        ctx.print(f"    Used Capacity: {human_size(used)}")
        for tier, n in sorted(cap["used"].items()):
            ctx.print(f"        Tier: {tier}  Size: {human_size(n)}")
        pct = (100.0 * used / total) if total else 0.0
        ctx.print(f"    Free Capacity: {human_size(total - used)} "
                  f"({100 - pct:.1f}% free)")
        return 0

    def _masters(self, ctx):
        """HA quorum view (docs/ha.md): one row per known master —
        role, term, last-applied journal sequence, lag behind the
        furthest member, tailer lag and last contact.  Exits nonzero
        when no primary is visible: a scriptable 'is failover stuck'
        probe."""
        report = ctx.meta_client().get_masters()
        leader = report.get("leader")
        masters = report.get("masters", [])
        ctx.print(f"Masters ({len(masters)} known, "
                  f"leader: {leader or 'NONE'}):")
        ctx.print(f"    {'Address':<24s} {'Role':>8s} {'Term':>6s} "
                  f"{'Applied':>10s} {'Lag':>6s} {'Tailer':>8s} "
                  f"{'Contact':>8s}")
        for m in sorted(masters, key=lambda r: r.get("address", "")):
            # EMBEDDED members without a registry row (per-folder
            # registries): the leader still knows how far they have
            # replicated — show match_index rather than a blank
            seq = m.get("sequence")
            if seq is None:
                seq = m.get("match_index")
            lag = m.get("lag_entries")
            tailer = m.get("tailer_lag_s")
            contact = m.get("last_contact_s")
            mark = " *" if m.get("address") == leader else ""
            ctx.print(
                f"    {str(m.get('address', '?')) + mark:<24s} "
                f"{m.get('role', '?'):>8s} "
                f"{m.get('term', '-'):>6} "
                f"{seq if seq is not None else '-':>10} "
                f"{lag if lag is not None else '-':>6} "
                f"{f'{tailer:.1f}s' if tailer is not None else '-':>8s} "
                f"{f'{contact:.1f}s' if contact is not None else '-':>8s}")
        has_primary = any(m.get("role") == "PRIMARY" for m in masters)
        if not has_primary:
            ctx.eprint("WARN: no PRIMARY visible — failover in "
                       "progress or quorum lost (docs/ha.md)")
        return 0 if has_primary else 1

    def _capacity(self, ctx):
        workers = ctx.block_client().get_worker_infos(
            include_lost=True, include_quarantined=True)
        ctx.print(f"{'Worker Name':<28s} {'Last Heartbeat':>14s} "
                  f"{'Storage':>9s} {'Total':>12s} {'Used':>12s} "
                  f"{'State':>8s}")
        for w in workers:
            first = True
            tiers = sorted(set(list(w.capacity_bytes_on_tiers)
                               + list(w.used_bytes_on_tiers)))
            for tier in tiers or ["-"]:
                total = w.capacity_bytes_on_tiers.get(tier, 0)
                used = w.used_bytes_on_tiers.get(tier, 0)
                namecol = (f"{w.address.host}:{w.address.rpc_port}"
                           if first else "")
                ctx.print(f"{namecol:<28s} "
                          f"{w.last_contact_ms if first else '':>14} "
                          f"{tier:>9s} {human_size(total):>12s} "
                          f"{human_size(used):>12s} "
                          f"{(w.state if first else ''):>8}")
                first = False
        return 0

    def _ufs(self, ctx):
        for m in ctx.fs_client().get_mount_points():
            props = " ".join(f"{k}={v}" for k, v in m.properties.items())
            flags = []
            if m.read_only:
                flags.append("readonly")
            if m.shared:
                flags.append("shared")
            ctx.print(f"{m.ufs_uri} on {m.alluxio_path} "
                      f"(type={m.ufs_type or 'unknown'}"
                      + (", " + ", ".join(flags) if flags else "")
                      + (f", {props}" if props else "") + ")")
        return 0

    def _metrics(self, ctx):
        snap = ctx.meta_client().get_metrics()
        for k in sorted(snap):
            ctx.print(f"{k}  {snap[k]}")
        dropped = snap.get("Master.MetricsReportsDropped", 0)
        if dropped:
            ctx.print(f"WARN: {int(dropped)} metric reports dropped by "
                      f"the source cap — raise "
                      f"atpu.master.metrics.max.sources or hunt the "
                      f"source-name flood")
        blocked = snap.get("Master.MetricsReportsBlocked", 0)
        if blocked:
            ctx.print(f"WARN: {int(blocked)} metric reports refused "
                      f"from lost workers that never re-registered — "
                      f"run `fsadmin report health` and restart or "
                      f"remove the dead workers")
        repl_failed = snap.get("Master.ReplicationJobsFailed", 0)
        if repl_failed:
            ctx.print(f"WARN: {int(repl_failed)} replication job "
                      f"launches failed — is the job service up? "
                      f"deficits persist until launches succeed")
        repl_deferred = snap.get("Master.ReplicationJobsDeferred", 0)
        if repl_deferred:
            ctx.print(f"WARN: {int(repl_deferred)} replication jobs "
                      f"deferred by the in-flight cap "
                      f"(atpu.master.replication.max.inflight; "
                      f"currently "
                      f"{int(snap.get('Master.ReplicationJobsInflight', 0))}"
                      f" in flight) — expected during mass recovery, "
                      f"raise the cap if it never drains")
        native_fb = snap.get("Cluster.NativeFallbacks", 0)
        if native_fb:
            ctx.print(f"WARN: {int(native_fb)} native fastpath batches "
                      f"fell back to the pure-Python read path — a "
                      f"client without a working g++ toolchain loses "
                      f"the GIL-free plan executor quietly; check "
                      f"client hosts against docs/native.md, or set "
                      f"atpu.user.native.fastpath.enabled=false if "
                      f"that is intended")
        shed = snap.get("Master.RpcAdmissionShed", 0)
        if shed:
            # next to the other drop counters on purpose: shed RPCs
            # are load shedding working as designed, but the operator
            # reading drop counts must see them in the same place
            ctx.print(f"WARN: {int(shed)} RPCs shed by admission "
                      f"control (a principal exceeded "
                      f"atpu.master.rpc.admission.rate) — run "
                      f"`fsadmin report qos` for the per-principal "
                      f"breakdown; shed calls are also audit-logged "
                      f"with allowed=false")
        return 0

    def _qos(self, ctx):
        """Multi-tenant QoS posture: admission-control state with the
        per-principal admitted/shed table, plus every Worker.Qos* /
        Client.Qos* metric the cluster aggregates."""
        resp = ctx.meta_client().get_qos()
        adm = resp.get("admission", {})
        if not adm.get("enabled"):
            ctx.print("RPC admission control: DISABLED "
                      "(atpu.master.rpc.admission.enabled)")
        else:
            ctx.print(f"RPC admission control: rate "
                      f"{adm['rate_per_s']:g}/s per principal, burst "
                      f"{adm['burst']:g}, "
                      f"{int(adm.get('admitted_total', 0))} admitted / "
                      f"{int(adm.get('shed_total', 0))} shed")
            ctx.print(f"    exempt methods: "
                      f"{', '.join(adm.get('exempt', [])) or '(none)'}")
            rows = adm.get("principals", [])
            if rows:
                ctx.print(f"    {'principal':<24s} {'admitted':>10s} "
                          f"{'shed':>10s}")
                for r in rows:
                    ctx.print(f"    {r['principal']:<24s} "
                              f"{r['admitted']:>10d} {r['shed']:>10d}"
                              + ("   << throttled" if r["shed"] else ""))
            if adm.get("bucket_evictions"):
                ctx.print(f"    WARN: {adm['bucket_evictions']} "
                          f"principal buckets evicted by the "
                          f"max.principals cap — a principal flood is "
                          f"churning the limiter")
        qos_metrics = resp.get("metrics", {})
        if qos_metrics:
            ctx.print("QoS metrics (cluster-wide):")
            for k in sorted(qos_metrics):
                ctx.print(f"    {k}  {qos_metrics[k]}")
        else:
            ctx.print("No Worker.Qos*/Client.Qos* metrics reported — "
                      "enable atpu.worker.qos.enabled / "
                      "atpu.user.qos.stripe.limit to activate "
                      "data-plane QoS")
        return 0

    def _metastore(self, ctx):
        """Inode metastore posture (docs/metadata.md): backend kind and
        population for every backend; on LSM additionally the write
        path (memtable fill, WAL) and the read-amplification drivers
        (sorted runs, compaction debt) the metastore-compaction-debt
        health rule watches, plus the caching wrapper's hit ratio."""
        stats = ctx.meta_client().get_metastore_info().get("stats", {})
        if not stats:
            ctx.print("No metastore stats reported by this master")
            return 1
        ctx.print(f"Inode metastore: {stats.get('kind', '?')}")
        ctx.print(f"    Inodes: {int(stats.get('inodes', 0)):,}")
        if "cache_hit_ratio" in stats:
            ctx.print(f"    Hot-set cache: {int(stats.get('cache_entries', 0)):,} "
                      f"entries, hit ratio "
                      f"{float(stats.get('cache_hit_ratio', 0.0)):.2%} "
                      f"({int(stats.get('cache_hits', 0)):,} hits / "
                      f"{int(stats.get('cache_misses', 0)):,} misses)")
        if "memtable_bytes" in stats:
            ctx.print(f"    Memtable: {human_size(int(stats.get('memtable_bytes', 0)))} "
                      f"({int(stats.get('memtable_entries', 0)):,} entries), "
                      f"WAL {human_size(int(stats.get('wal_bytes', 0)))}")
            ctx.print(f"    Sorted runs: {int(stats.get('runs', 0))} "
                      f"({human_size(int(stats.get('run_bytes', 0)))} on disk)")
            ctx.print(f"    Flushes: {int(stats.get('flushes', 0))}  "
                      f"Compactions: {int(stats.get('compactions', 0))} "
                      f"({human_size(int(stats.get('compaction_bytes', 0)))} "
                      f"rewritten)")
        if "edges" in stats:
            ctx.print(f"    Edges: {int(stats.get('edges', 0)):,}")
        return 0

    def _history(self, ctx, args):
        """Time-resolved view of one metric: ASCII sparkline over the
        requested resolution plus a rollup table per reporting
        source."""
        mc = ctx.meta_client()
        if not args.metric:
            # same no-silent-swallow rule as run() applies across
            # categories: list mode ignores the series filters, so
            # accepting them would print the full unfiltered name
            # list as if they had applied
            extras = [what for what, given in (
                ("--source", args.source),
                ("--resolution", args.resolution != "raw"),
                ("--rate", args.rate)) if given]
            if extras:
                ctx.eprint(f"report history without a metric name "
                           f"lists recorded metrics and does not take "
                           f"{', '.join(extras)}")
                return 2
            resp = mc.get_metrics_history()
            st = resp.get("stats", {})
            ctx.print(f"{len(resp.get('names', []))} metrics recorded "
                      f"({st.get('series', 0)}/{st.get('max_series', 0)}"
                      f" series, {st.get('points', 0)} points)")
            for n in resp.get("names", []):
                ctx.print(f"    {n}")
            if st.get("dropped_samples"):
                ctx.print(f"WARN: {st['dropped_samples']} samples "
                          f"dropped by the series cap/allowlist")
            return 0
        resp = mc.get_metrics_history(
            args.metric, source=args.source,
            resolution=args.resolution, rate=args.rate)
        series = resp.get("series", [])
        if not series:
            ctx.print(f"no history recorded for '{args.metric}'"
                      + (f" from source '{args.source}'"
                         if args.source else ""))
            return 1
        suffix = "/s" if args.rate else ""
        for s in series:
            pts = s["points"]
            if s["resolution"] == "raw" or args.rate:
                values = [v for _, v in pts]
            else:
                values = [b["mean"] for b in pts]
            head = (f"{s['name']} [{s['source']}] "
                    f"({s['resolution']}, {len(pts)} points)")
            if s.get("ended_at"):
                head += "  [source ENDED — worker lost]"
            ctx.print(head)
            if not values:
                ctx.print("    (empty window)")
                continue
            ctx.print(f"    {sparkline(values)}")
            if s["resolution"] == "raw" or args.rate:
                lo, hi, last = min(values), max(values), values[-1]
            else:
                # true per-bucket extremes and final value, not the
                # means the sparkline plots — a one-bucket spike must
                # not understate the headline max, and the headline
                # last must match the rollup table's last column below
                lo = min(b["min"] for b in pts)
                hi = max(b["max"] for b in pts)
                last = pts[-1]["last"]
            ctx.print(f"    min={lo:.4g}{suffix} "
                      f"max={hi:.4g}{suffix} "
                      f"last={last:.4g}{suffix}")
            if s["resolution"] != "raw" and not args.rate:
                ctx.print(f"    {'bucket':<21s} {'count':>6s} "
                          f"{'mean':>10s} {'min':>10s} {'max':>10s} "
                          f"{'last':>10s}")
                for b in pts[-12:]:
                    when = time.strftime("%m-%d %H:%M:%S",
                                         time.localtime(b["ts"]))
                    ctx.print(f"    {when:<21s} {b['count']:>6d} "
                              f"{b['mean']:>10.4g} {b['min']:>10.4g} "
                              f"{b['max']:>10.4g} {b['last']:>10.4g}")
        return 0

    def _health(self, ctx, args):
        """Ranked verdicts from the master's continuous health-rule
        engine (the cluster doctor)."""
        resp = ctx.meta_client().get_health()
        ctx.print(f"Cluster health: {resp['status']}")
        alerts = resp.get("alerts", [])
        for a in alerts:
            dur = ""
            if a.get("fired_at") and resp.get("evaluated_at"):
                dur = (f" (firing "
                       f"{max(0, resp['evaluated_at'] - a['fired_at']):.0f}s)")
            ctx.print(f"  [{a['severity'].upper()}] {a['rule']} "
                      f"on {a['subject']}{dur}")
            ctx.print(f"      {a['summary']}")
            ctx.print(f"      value {a['value']:.4g} vs threshold "
                      f"{a['threshold']:.4g} over {a['window_s']:.0f}s")
            ctx.print(f"      remediation: {a['remediation']}")
        for a in resp.get("pending", []):
            ctx.print(f"  [pending] {a['rule']} on {a['subject']}: "
                      f"{a['summary']}")
        for a in resp.get("recently_resolved", []):
            ctx.print(f"  [resolved] {a['rule']} on {a['subject']}")
        if not alerts:
            ctx.print(f"  no alerts firing — "
                      f"{len(resp.get('rules', []))} rules watching")
        self._remediation(ctx, resp.get("remediation"))
        return 0 if resp["status"] in ("OK", "WARN") else 1

    @staticmethod
    def _remediation(ctx, rem):
        """Self-healing timeline: every audit row is one
        cause -> action -> resolution line, so the operator reads what
        the engine did (or would do, in dry-run) and why, in order."""
        if not rem:
            return  # engine disabled: report is byte-identical to PR-5
        mode = "DRY-RUN" if rem.get("dry_run") else "active"
        ctx.print(f"Self-healing ({mode}): "
                  f"{rem.get('actions_in_window', 0)}/"
                  f"{rem.get('max_actions_per_window', 0)} actions in "
                  f"window, {len(rem.get('quarantined', []))} "
                  f"quarantined, {len(rem.get('overlay', {}))} tuning "
                  f"overlay key(s) pushed")
        for q in rem.get("quarantined", []):
            state = "probation" if q.get("probation_since") else \
                "quarantined"
            ctx.print(f"  [{state}] {q['subject']} "
                      f"(cause: {q['rule']})")
        for k, v in sorted(rem.get("overlay", {}).items()):
            ctx.print(f"  [overlay] {k} = {v}")
        audit = rem.get("audit", [])
        for a in audit[-12:]:
            when = time.strftime("%m-%d %H:%M:%S",
                                 time.localtime(a["at"]))
            resolution = ""
            if a.get("reverted_at"):
                resolution = (" -> reverted "
                              + time.strftime(
                                  "%H:%M:%S",
                                  time.localtime(a["reverted_at"])))
            elif a.get("resolved_at"):
                resolution = " -> alert resolved"
            ctx.print(f"  {when}  {a['rule']} on {a['subject']} -> "
                      f"{a['action']} [{a['outcome']}]{resolution}")
            ctx.print(f"      {a['summary']}")
        if not audit:
            ctx.print("  no actions audited yet")

    def _stall(self, ctx):
        """Input doctor: ranked per-tier attribution of loader input
        waits (``Client.InputStall*`` metrics, shipped to the master on
        the metrics heartbeat and summed into ``Cluster.*``)."""
        snap = ctx.meta_client().get_metrics()

        def bucket_stats(kind):
            # prefer the cluster roll-up (sums every reporting client);
            # fall back to this master's own Client.* metrics (the
            # in-process / single-node case)
            out = {}
            for prefix in (f"Cluster.InputStall{kind}.",
                           f"Client.InputStall{kind}."):
                for k, v in snap.items():
                    if k.startswith(prefix):
                        out[k[len(prefix):]] = v
                if out:
                    return out
            return out

        waits_us = bucket_stats("Us")
        counts = bucket_stats("Count")
        sizes = bucket_stats("Bytes")
        total_s = sum(waits_us.values()) / 1e6
        ctx.print("Input-stall attribution (input doctor):")
        if not waits_us:
            ctx.print("    no input-stall samples recorded — run a "
                      "DeviceBlockLoader epoch with metrics collection "
                      "enabled (atpu.user.metrics.collection.enabled)")
            # table reads stall no loader; their route split still tells
            # whether planned projections landed on the fast planes
            self._stall_table_routes(ctx, snap)
            return 0
        ctx.print(f"    {'tier':<10s} {'waits':>8s} {'stalled':>12s} "
                  f"{'bytes':>12s} {'share':>7s}")
        named_s = 0.0
        for b, us in sorted(waits_us.items(), key=lambda kv: -kv[1]):
            s = us / 1e6
            if b != "unknown":
                named_s += s
            share = (100.0 * s / total_s) if total_s else 0.0
            ctx.print(f"    {b:<10s} {int(counts.get(b, 0)):>8d} "
                      f"{s:>11.3f}s "
                      f"{human_size(int(sizes.get(b, 0))):>12s} "
                      f"{share:>6.1f}%")
        attributed = (100.0 * named_s / total_s) if total_s else 100.0
        ctx.print(f"    attributed to a named tier: {attributed:.1f}% "
                  f"of {total_s:.3f}s total wait")
        # op-size columns: the same stalls re-cut by read size — a
        # le4k-dominated profile is per-op RPC overhead (see
        # `report readpath`), not bandwidth
        size_us = bucket_stats("SizeUs")
        size_counts = bucket_stats("SizeCount")
        if size_us:
            from alluxio_tpu.metrics.stall import SIZE_BUCKETS

            ctx.print(f"    {'op size':<10s} {'waits':>8s} "
                      f"{'stalled':>12s} {'share':>7s}")
            for b in SIZE_BUCKETS:
                us = size_us.get(b)
                if not us:
                    continue
                s = us / 1e6
                share = (100.0 * s / total_s) if total_s else 0.0
                ctx.print(f"    {b:<10s} "
                          f"{int(size_counts.get(b, 0)):>8d} "
                          f"{s:>11.3f}s {share:>6.1f}%")
        # the small-read plane check: the le4k row re-cut by serving
        # tier. All-shm means the zero-copy plane landed; remote-heavy
        # means batching/co-location is the lever (docs/small_reads.md)
        cross_us = bucket_stats("CrossUs")
        cross_counts = bucket_stats("CrossCount")
        le4k = {k.split(".")[0]: v for k, v in cross_us.items()
                if k.endswith(".le4k") and v}
        if le4k:
            le4k_s = sum(le4k.values()) / 1e6
            ctx.print(f"    le4k by tier (shm vs remote vs ufs, "
                      f"{le4k_s:.3f}s):")
            for t, us in sorted(le4k.items(), key=lambda kv: -kv[1]):
                s = us / 1e6
                share = (100.0 * us / sum(le4k.values()))
                n = int(cross_counts.get(f"{t}.le4k", 0))
                ctx.print(f"      {t:<8s} {n:>8d} {s:>11.3f}s "
                          f"{share:>6.1f}%")
        self._stall_table_routes(ctx, snap)
        # cluster mean first (the fleet view, averaged across reporting
        # clients); the master's own gauge only exists when a loader
        # ran in-process and would shadow the fleet with a stale 0.0
        frac = snap.get("Cluster.InputBoundFraction",
                        snap.get("Client.InputBoundFraction"))
        if frac is not None:
            ctx.print(f"    rolling input-bound fraction: {frac:.2f}")
        top = max(waits_us, key=waits_us.get)
        from alluxio_tpu.metrics.stall import BUCKET_ADVICE

        share = (100.0 * waits_us[top] / 1e6 / total_s) if total_s else 0.0
        ctx.print(f"Verdict: top bottleneck is '{top}' ({share:.0f}% of "
                  f"stall) — "
                  f"{BUCKET_ADVICE.get(top, 'no advice for this tier')}")
        return 0

    @staticmethod
    def _stall_table_routes(ctx, snap):
        # the table-read plane check: planned projection bytes re-cut by
        # serving route. shm-heavy means same-host zero-copy landed;
        # stream-heavy means the range executor never engaged the batch
        # or striped planes (docs/table_reads.md)
        route_bytes = {}
        for prefix in ("Cluster.TableProjectionRouteBytes.",
                       "Client.TableProjectionRouteBytes."):
            for k, v in snap.items():
                if k.startswith(prefix) and v:
                    route_bytes[k[len(prefix):]] = v
            if route_bytes:
                break
        if route_bytes:
            route_total = sum(route_bytes.values())
            ctx.print(f"    table projection by route "
                      f"({human_size(route_total)} planned):")
            for r, nbytes in sorted(route_bytes.items(),
                                    key=lambda kv: -kv[1]):
                share = 100.0 * nbytes / route_total
                ctx.print(f"      {r:<8s} {human_size(int(nbytes)):>12s} "
                          f"{share:>6.1f}%")

    def _readpath(self, ctx):
        """Read-path microscope: ranked per-phase critical-path profile
        over the master's sampled traces (``get_trace_profile``). Run
        with tracing on (``fsadmin trace --on``) while a workload
        reads — the table names what each read was actually blocked
        on, phase by phase (docs/observability.md)."""
        resp = ctx.meta_client().get_trace_profile(root_prefix="atpu.")
        if not resp.get("enabled"):
            ctx.eprint("tracing is off — enable with "
                       "`fsadmin trace --on`, run the workload, then "
                       "re-run this report")
        prof = resp.get("profile") or {}
        n = prof.get("traces_analyzed", 0)
        ctx.print(f"Read-path critical-path profile "
                  f"({n} traces analyzed):")
        if not n:
            ctx.print("    no complete traces stitched yet — spans "
                      "arrive on the metrics heartbeat; wait one "
                      "interval and retry")
            return 0
        ctx.print(f"    wall: total {prof['wall_ms_total']:.1f} ms, "
                  f"p50 {prof['wall_ms_p50']:.2f} ms, "
                  f"p99 {prof['wall_ms_p99']:.2f} ms; "
                  f"{prof['attributed_pct']:.1f}% attributed to "
                  f"named phases")
        ctx.print(f"    {'span/phase':<48s} {'count':>6s} "
                  f"{'total':>10s} {'p50':>8s} {'p99':>8s} "
                  f"{'share':>7s}")
        for row in prof.get("phases", ()):
            ctx.print(f"    {row['key']:<48s} {row['count']:>6d} "
                      f"{row['total_ms']:>8.1f}ms "
                      f"{row['p50_ms']:>6.2f}ms "
                      f"{row['p99_ms']:>6.2f}ms "
                      f"{row['pct']:>6.1f}%")
        return 0

    def _jobservice(self, ctx):
        """Job-service health + activity (reference ``fsadmin report
        jobservice``, ``cli/fsadmin/report/
        JobServiceMetricsCommand.java``)."""
        jc = ctx.job_client()
        workers = jc.list_workers()
        ctx.print(f"Job workers: {len(workers)}")
        for w in sorted(workers, key=lambda w: w["worker_id"]):
            h = w.get("health") or {}
            ctx.print(
                f"  {w['hostname']} (id={w['worker_id']}): "
                f"active {h.get('num_active_tasks', 0)}/"
                f"{h.get('task_pool_size', 0)} tasks, "
                f"{h.get('unfinished_tasks', 0)} unfinished, "
                f"load {h.get('load_avg', 0.0):.2f}")
        jobs = jc.list_jobs()
        by_status: dict = {}
        for j in jobs:
            by_status[j.status] = by_status.get(j.status, 0) + 1
        ctx.print("Status: " + (", ".join(
            f"{s}={n}" for s, n in sorted(by_status.items()))
            or "(no jobs)"))
        newest = sorted(jobs, key=lambda j: j.last_updated_ms,
                        reverse=True)[:10]
        for j in newest:
            err = f" error={j.error_message}" if j.error_message else ""
            ctx.print(f"  job {j.job_id} {j.name or '?'}: "
                      f"{j.status}{err}")
        return 0


@ADMIN_SHELL.register
class DoctorCommand(Command):
    name = "doctor"
    description = "Show configuration and cluster health warnings."

    def configure(self, p):
        p.add_argument("category", nargs="?", default="configuration",
                       choices=["configuration"])

    def run(self, args, ctx):
        # cluster-wide consistency report (ServerConfigurationChecker);
        # degrade gracefully against masters without the RPC
        try:
            report = ctx.meta_client().get_config_report()
        except Exception:  # noqa: BLE001
            report = {"status": "UNAVAILABLE", "errors": [], "warns": []}
        ctx.print(f"Server-side configuration check: {report['status']}")
        for e in report.get("errors", []):
            ctx.print(f"ERROR: {e}")
        for w in report.get("warns", []):
            ctx.print(f"WARN: {w}")
        # local-vs-cluster diff
        server_conf = ctx.meta_client().get_configuration()
        server = server_conf.get("properties", {})
        local = ctx.conf.to_map(min_source=Source.SITE_PROPERTY)
        issues = 0
        for key, val in sorted(server.items()):
            mine = local.get(key)
            if mine is not None and str(mine) != str(val):
                ctx.print(f"WARN: {key} differs: server='{val}' "
                          f"client='{mine}'")
                issues += 1
        if issues == 0 and report["status"] == "PASSED":
            ctx.print("No configuration conflicts found.")
        # quorum health (EMBEDDED journal only; silent elsewhere)
        try:
            q = ctx.meta_client().get_quorum_info()
        except Exception:  # noqa: BLE001 - LOCAL/UFS journal
            q = None
        if q is not None:
            ctx.print(f"Quorum: leader={q['leader']} term={q['term']} "
                      f"members={len(q['members'])}")
            # match_index is only meaningful on a settled LEADER (it
            # resets to 0 at election and is absent on followers) —
            # lag analysis from any other respondent is a false alarm
            me = next((m for m in q["members"]
                       if m["address"] == "self"), None)
            if me is not None and me["role"] == "LEADER":
                for m in q["members"]:
                    if m is me:
                        continue
                    if m["match_index"] + 50 < q["commit_index"]:
                        ctx.print(
                            f"WARN: quorum member {m['node_id']} lags "
                            f"{q['commit_index'] - m['match_index']} "
                            f"entries behind the commit index")
        # process stall telemetry (pause monitor)
        try:
            metrics = ctx.meta_client().get_metrics()
            pauses = metrics.get("Process.SeverePauses", 0)
            maxp = metrics.get("Process.MaxPauseSeconds", 0.0)
            if pauses or (maxp and maxp >= 1.0):
                ctx.print(f"WARN: master stalled (max pause "
                          f"{maxp:.2f}s, severe pauses {int(pauses)}) — "
                          f"GC/CFS/host pressure can trip elections")
        except Exception:  # noqa: BLE001
            pass
        return 0 if report["status"] != "FAILED" else 1


@ADMIN_SHELL.register
class PathConfCommand(Command):
    name = "pathConf"
    description = "Manage per-path configuration defaults."

    def configure(self, p):
        sub = p.add_subparsers(dest="op", required=True)
        sub.add_parser("list")
        show = sub.add_parser("show")
        show.add_argument("path")
        add = sub.add_parser("add")
        add.add_argument("--property", action="append", default=[],
                         dest="props", help="key=value (repeatable)")
        add.add_argument("path")
        rm = sub.add_parser("remove")
        rm.add_argument("--keys", default=None,
                        help="comma-separated keys (all when omitted)")
        rm.add_argument("path")

    def run(self, args, ctx):
        mc = ctx.meta_client()
        if args.op == "list":
            for path in sorted(mc.get_path_conf()["properties"]):
                ctx.print(path)
        elif args.op == "show":
            props = mc.get_path_conf()["properties"].get(args.path, {})
            for k in sorted(props):
                ctx.print(f"{k}={props[k]}")
        elif args.op == "add":
            kv = {}
            for p in args.props:
                if "=" not in p:
                    raise CommandError(
                        f"--property must be key=value, got {p!r}")
                k, _, v = p.partition("=")
                kv[k] = v
            mc.set_path_conf(args.path, kv)
            ctx.print(f"Properties of path {args.path} updated")
        elif args.op == "remove":
            keys = args.keys.split(",") if args.keys else None
            mc.remove_path_conf(args.path, keys)
            ctx.print(f"Properties of path {args.path} removed")
        return 0


@ADMIN_SHELL.register
class JournalCommand(Command):
    name = "journal"
    description = "Journal operations: checkpoint | dump."

    def configure(self, p):
        p.add_argument("op", choices=["checkpoint", "dump", "quorum",
                                      "migrate"])
        p.add_argument("--folder", default=None,
                       help="journal dir for dump/migrate "
                            "(default: configured)")
        p.add_argument("--start", type=int, default=0)
        p.add_argument("--end", type=int, default=None)
        p.add_argument("--transfer", default="",
                       help="quorum: hand leadership to this member id")
        p.add_argument("--to", default="", choices=["", "EMBEDDED", "LOCAL"],
                       help="migrate: target journal flavor (OFFLINE — "
                            "stop every master first)")
        p.add_argument("--dest", default="",
                       help="migrate: destination journal folder "
                            "(default: same folder)")
        p.add_argument("--addresses", default="",
                       help="migrate to EMBEDDED: quorum member "
                            "addresses, comma separated (default: "
                            "atpu.master.embedded.journal.addresses)")
        p.add_argument("--member", default="",
                       help="migrate to LOCAL: source quorum member id "
                            "(default: the freshest)")

    def run(self, args, ctx):
        if args.op == "migrate":
            return self._migrate(args, ctx)
        if args.op == "checkpoint":
            ctx.meta_client().checkpoint()
            ctx.print("Successfully took a checkpoint on the primary master")
            return 0
        if args.op == "quorum":
            mc = ctx.meta_client()
            if args.transfer:
                resp = mc.transfer_quorum_leadership(args.transfer)
                ok = resp.get("transferred")
                ctx.print(f"leadership transfer to {args.transfer}: "
                          f"{'done' if ok else 'FAILED'}")
                return 0 if ok else 1
            info = mc.get_quorum_info()
            ctx.print(f"term {info['term']}  leader {info['leader']}  "
                      f"commit {info['commit_index']}")
            for m in info["members"]:
                ctx.print(f"  {m['node_id']:<24} {m['role']:<9} "
                          f"match={m['match_index']} ({m['address']})")
            return 0
        from alluxio_tpu.conf import Keys
        from alluxio_tpu.journal.tool import dump_journal

        folder = args.folder or str(ctx.conf.get(
            Keys.MASTER_JOURNAL_FOLDER))
        n = dump_journal(folder, ctx.out, start_seq=args.start,
                         end_seq=args.end)
        ctx.print(f"({n} entries)")
        return 0

    def _migrate(self, args, ctx):
        """Offline LOCAL/UFS <-> EMBEDDED conversion (reference:
        ``JournalUpgrader.java:61`` + JournalMigrationIntegrationTest)."""
        from alluxio_tpu.conf import Keys
        from alluxio_tpu.journal import migrate as mig

        folder = args.folder or str(ctx.conf.get(
            Keys.MASTER_JOURNAL_FOLDER))
        dest = args.dest or folder
        try:
            if args.to == "EMBEDDED":
                configured = ctx.conf.get(
                    Keys.MASTER_EMBEDDED_JOURNAL_ADDRESSES) or ""
                if isinstance(configured, (list, tuple)):
                    configured = ",".join(configured)
                addresses = [a.strip() for a in
                             (args.addresses or str(configured)).split(",")
                             if a.strip()]
                out = mig.local_to_embedded(folder, dest, addresses)
                ctx.print(
                    f"migrated LOCAL journal {folder} -> EMBEDDED "
                    f"{dest} ({len(out['members'])} members, checkpoint "
                    f"seq {out['checkpoint_seq']}, {out['entries']} "
                    f"tail entries)")
            elif args.to == "LOCAL":
                out = mig.embedded_to_local(folder, dest,
                                            node_id=args.member)
                ctx.print(
                    f"migrated EMBEDDED member {out['source_member']} "
                    f"-> LOCAL {dest} (checkpoint seq "
                    f"{out['checkpoint_seq']}, {out['entries']} tail "
                    f"entries)")
            else:
                ctx.print("journal migrate needs --to EMBEDDED|LOCAL")
                return 1
        except mig.MigrationError as e:
            ctx.print(f"migration failed: {e}")
            return 1
        return 0


@ADMIN_SHELL.register
class BackupCommand(Command):
    name = "backup"
    description = "Write a full metadata backup on the primary master."

    def configure(self, p):
        p.add_argument("directory", nargs="?", default=None)

    def run(self, args, ctx):
        resp = ctx.meta_client().backup(args.directory)
        ctx.print(f"Backup Host: {ctx.master_address}")
        ctx.print(f"Backup URI: {resp['backup_uri']}")
        ctx.print(f"Backup Entry Count: {resp['entry_count']}")
        return 0


@ADMIN_SHELL.register
class GetConfCommand(Command):
    name = "getConf"
    description = "Print cluster configuration (optionally one key)."

    def configure(self, p):
        p.add_argument("--source", action="store_true",
                       help="also print each property's source")
        p.add_argument("key", nargs="?")

    def run(self, args, ctx):
        from alluxio_tpu.conf.property_key import mask_credential

        resp = ctx.meta_client().get_configuration(sources=args.source)
        props = resp["properties"]
        srcs = resp.get("sources") or {}
        # display surface: mask credential values (reference
        # DisplayType.CREDENTIALS handling in GetConfCommand)
        props = {k: mask_credential(k, v) for k, v in props.items()}
        if args.key:
            if args.key in props:
                suffix = (f"  (source: {srcs[args.key]})"
                          if args.key in srcs else "")
                ctx.print(f"{props[args.key]}{suffix}")
                return 0
            try:
                v = ctx.conf.get(args.key)
            except KeyError:
                v = None
            if v is None:
                ctx.eprint(f"{args.key} is not set")
                return 1
            ctx.print(mask_credential(args.key, v))
            return 0
        for k in sorted(props):
            suffix = f"  (source: {srcs[k]})" if k in srcs else ""
            ctx.print(f"{k}={props[k]}{suffix}")
        return 0


@ADMIN_SHELL.register
class MetricsCommand(Command):
    name = "metrics"
    description = "Print master metrics matching an optional filter."

    def configure(self, p):
        p.add_argument("filter", nargs="?", default="")

    def run(self, args, ctx):
        snap = ctx.meta_client().get_metrics()
        for k in sorted(snap):
            if args.filter in k:
                ctx.print(f"{k}  {snap[k]}")
        return 0


@ADMIN_SHELL.register
class LogLevelCommand(Command):
    name = "logLevel"
    description = ("Get or set the master's runtime log level "
                   "(reference: cli/LogLevel.java).")

    def configure(self, p):
        p.add_argument("--logName", default="",
                       help="logger name (default: root)")
        p.add_argument("--level", default="",
                       help="new level (DEBUG/INFO/WARNING/ERROR); "
                            "omit to read the current level")

    def run(self, args, ctx):
        mc = ctx.meta_client()
        if args.level:
            resp = mc.set_log_level(args.level, logger=args.logName)
            ctx.print(f"{resp['logger']} -> {resp['level']}")
        else:
            resp = mc.get_log_level(args.logName)
            ctx.print(f"{resp['logger']} = {resp['level']}")
        return 0


@ADMIN_SHELL.register
class TraceCommand(Command):
    name = "trace"
    description = ("Toggle span tracing and dump recent master spans "
                   "(spans also serve at /api/v1/master/trace).")

    def configure(self, p):
        g = p.add_mutually_exclusive_group()
        g.add_argument("--on", action="store_true",
                       help="enable tracing (clears the ring)")
        g.add_argument("--off", action="store_true",
                       help="disable tracing")
        p.add_argument("--limit", type=int, default=25,
                       help="spans to print (most recent first)")
        p.add_argument("--prefix", default="",
                       help="only spans whose name starts with this")
        p.add_argument("--critical-path", default="", metavar="TRACE_ID",
                       help="print one trace's blocking chain with "
                            "per-phase attribution")
        p.add_argument("--no-fanout", action="store_true",
                       help="query only one master instead of every "
                            "configured HA member")

    def run(self, args, ctx):
        mc = ctx.meta_client()
        if args.on:
            mc.set_trace_enabled(True, clear=True)
            ctx.print("tracing enabled")
            return 0
        if args.off:
            mc.set_trace_enabled(False)
            ctx.print("tracing disabled")
            return 0
        from alluxio_tpu.utils.trace_fanout import (
            master_endpoints, merge_stitched, peer_traces)

        # spans land on whichever master each node heartbeats to (PR-11
        # standby metrics reads): on an HA list, ask every member
        fanout = (not args.no_fanout
                  and len(master_endpoints(ctx.conf)) > 1)
        if args.critical_path:
            return self._critical_path(ctx, mc, args.critical_path,
                                       fanout)
        resp = mc.get_trace(limit=args.limit, prefix=args.prefix)
        if fanout:
            resp = {"enabled": resp["enabled"],
                    **merge_stitched(resp, peer_traces(
                        ctx.conf, limit=args.limit,
                        prefix=args.prefix))}
        ctx.print(f"tracing: {'on' if resp['enabled'] else 'off'} "
                  f"({len(resp['spans'])} spans)")
        for s in resp["spans"][:args.limit]:
            dur = s["duration_ms"]
            shown = "-" if dur is None else f"{round(dur, 2)}"
            tid = (s.get("trace_id") or "")[:8]
            ctx.print(f"  {s['name']:<40} {shown:>9} ms  "
                      f"trace={tid} src={s.get('source', 'local')} "
                      f"thread={s['thread']}"
                      + (f"  ERROR {s['error']}" if s["error"] else ""))
        for t in resp.get("traces", [])[:10]:
            dur = t.get("duration_ms")
            ctx.print(f"  trace {t['trace_id'][:8]}: {t['spans']} spans "
                      f"across {','.join(t['sources'])} "
                      f"root={t.get('root') or '?'} "
                      f"({'-' if dur is None else round(dur, 2)} ms)")
        return 0

    def _critical_path(self, ctx, mc, trace_id, fanout):
        """Blocking-chain view of one trace. With fan-out the spans are
        merged from every HA member first and analyzed locally —
        otherwise the master runs the analysis server-side."""
        if fanout:
            from alluxio_tpu.utils.critical_path import analyze_trace
            from alluxio_tpu.utils.trace_fanout import (
                merge_stitched, peer_traces)

            base = mc.get_trace(limit=4000, trace_id=trace_id)
            merged = merge_stitched(base, peer_traces(
                ctx.conf, limit=4000, trace_id=trace_id))
            cp = analyze_trace(merged["spans"])
        else:
            cp = mc.get_trace_profile(
                trace_id=trace_id).get("critical_path")
        if not cp:
            ctx.eprint(f"no spans recorded for trace {trace_id} — is "
                       f"tracing on, and has a metrics heartbeat "
                       f"shipped the spans yet?")
            return 1
        ctx.print(f"trace {cp['trace_id'][:16]}: root {cp['root']} "
                  f"({cp['wall_ms']:.2f} ms wall, "
                  f"{cp['attributed_pct']:.1f}% attributed to named "
                  f"phases)")
        ctx.print("  blocking chain (critical path):")
        for row in cp.get("spans_on_path", ()):
            phases = row.get("phases") or {}
            detail = ", ".join(f"{k}={v:.2f}ms" for k, v in
                               sorted(phases.items(),
                                      key=lambda kv: -kv[1]))
            ctx.print(f"    +{row['start_off_ms']:>8.2f}ms "
                      f"{row['span']:<40s} "
                      f"src={row.get('source') or '?':<10s} "
                      f"self={row['self_ms']:.2f}ms"
                      + (f"  [{detail}]" if detail else ""))
        ctx.print("  top segments:")
        segs = sorted(cp.get("segments", {}).items(),
                      key=lambda kv: -kv[1])
        for key, ms in segs[:15]:
            share = (100.0 * ms / cp["wall_ms"]) if cp["wall_ms"] else 0.0
            ctx.print(f"    {key:<48s} {ms:>8.2f}ms {share:>5.1f}%")
        return 0


def main(argv=None) -> int:
    return ADMIN_SHELL.run(sys.argv[1:] if argv is None else argv)
