"""JobShell: ``alluxio-tpu job <command>``.

Re-design of ``shell/src/main/java/alluxio/cli/job/JobShell.java`` +
``job/command/*``: list/inspect/cancel jobs against the job master.
"""

from __future__ import annotations

import sys
import time

from alluxio_tpu.shell.command import Command, Shell

JOB_SHELL = Shell("job", "Interact with the job service.")


def _fmt_job(info) -> str:
    when = time.strftime("%m-%d-%Y %H:%M:%S",
                         time.localtime(info.last_updated_ms / 1000))
    return (f"{info.job_id:<8d} {info.name:<12s} "
            f"{info.status:<10s} {when}"
            + (f"  {info.error_message}" if info.error_message else ""))


@JOB_SHELL.register
class LsCommand(Command):
    name, description = "ls", "List jobs known to the job master."

    def run(self, args, ctx):
        for info in ctx.job_client().list_jobs():
            ctx.print(_fmt_job(info))
        return 0


@JOB_SHELL.register
class StatCommand(Command):
    name, description = "stat", "Show one job's status (and task detail)."

    def configure(self, p):
        p.add_argument("-v", action="store_true", dest="verbose")
        p.add_argument("job_id", type=int)

    def run(self, args, ctx):
        info = ctx.job_client().get_status(args.job_id)
        ctx.print(f"ID: {info.job_id}")
        ctx.print(f"Name: {info.name}")
        ctx.print(f"Status: {info.status}")
        if info.error_message:
            ctx.print(f"Error: {info.error_message}")
        if args.verbose:
            for t in info.tasks:
                ctx.print(f"  task {t.task_id} on worker {t.worker_id}: "
                          f"{t.status}"
                          + (f" ({t.error_message})" if t.error_message
                             else ""))
        return 0


@JOB_SHELL.register
class CancelCommand(Command):
    name, description = "cancel", "Cancel a running job."

    def configure(self, p):
        p.add_argument("job_id", type=int)

    def run(self, args, ctx):
        ctx.job_client().cancel(args.job_id)
        ctx.print(f"Job {args.job_id} canceled")
        return 0


@JOB_SHELL.register
class LeaderCommand(Command):
    name, description = "leader", "Print the job master address."

    def run(self, args, ctx):
        ctx.job_client().list_plan_types()  # verifies it is serving
        ctx.print(ctx.job_master_address)
        return 0


def main(argv=None) -> int:
    return JOB_SHELL.run(sys.argv[1:] if argv is None else argv)
