"""validateEnv / validateHms: task-based pre-flight validation.

Env-adapted analogue of the reference's validation tools
(``integration/tools/validation/.../{PortAvailabilityValidationTask,
RamDiskMountPrivilegeValidationTask,NativeLibValidationTask,
SshValidationTask,ClusterConfConsistencyValidationTask}.java`` and
``integration/tools/hms/.../HmsValidationTool.java:32`` with its
UriCheck/CreateHmsClient/MetastoreValidation/DatabaseValidation/
TableValidation tasks): each check is a named task returning
OK/WARNING/FAILED/SKIPPED plus advice, so an operator can vet a node
(or a metastore) before starting processes — instead of discovering a
bad port/dir/URI at boot.
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.conf.property_key import Templates

OK = "OK"
WARNING = "WARNING"
FAILED = "FAILED"
SKIPPED = "SKIPPED"


@dataclass
class TaskResult:
    """Reference ``ValidationTaskResult``: name + state + advice."""

    name: str
    state: str
    message: str = ""
    advice: str = ""


@dataclass
class ValidationTool:
    """A named collection of tasks; ``run_all`` never raises — a task
    blowing up becomes its own FAILED row (the reference wraps each
    task the same way)."""

    name: str
    tasks: List["tuple[str, Callable[[], TaskResult]]"] = \
        field(default_factory=list)

    def add(self, name: str, fn: Callable[[], TaskResult]) -> None:
        self.tasks.append((name, fn))

    def run_all(self) -> List[TaskResult]:
        out = []
        for name, fn in self.tasks:
            try:
                out.append(fn())
            except Exception as e:  # noqa: BLE001 task isolation
                out.append(TaskResult(name, FAILED,
                                      f"{type(e).__name__}: {e}"))
        return out


# -- env tasks --------------------------------------------------------

def _check_port(name: str, host: str, port: int) -> TaskResult:
    """A port is OK if free (process can bind it later) or if something
    already accepts connections on it (assumed to be ours, reported as
    WARNING so the operator decides). A host that is not local at all
    (EADDRNOTAVAIL — e.g. the master hostname checked from a worker
    node) can only be probed by connecting; nothing serving there yet
    is expected pre-start, not a failure."""
    import errno

    try:
        with socket.socket() as s:
            s.bind((host, port))
        return TaskResult(name, OK, f"{host}:{port} free")
    except OSError as e:
        host_is_local = e.errno != errno.EADDRNOTAVAIL
    try:
        with socket.create_connection((host, port), timeout=2):
            return TaskResult(
                name, WARNING, f"{host}:{port} already serving",
                advice="fine if this is the running cluster; otherwise "
                       "another process owns the port")
    except OSError as e:
        if not host_is_local:
            return TaskResult(
                name, SKIPPED,
                f"{host} is not a local address and nothing serves "
                f"{host}:{port} yet — check from that host")
        return TaskResult(name, FAILED,
                          f"{host}:{port} bound but not accepting: {e}",
                          advice="free the port or change the key")


def _check_dir(name: str, path: str, min_free_bytes: int) -> TaskResult:
    if not path:
        return TaskResult(name, SKIPPED, "no path configured")
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, ".atpu-validate")
        with open(probe, "w") as f:
            f.write("ok")
        os.remove(probe)
    except OSError as e:
        return TaskResult(name, FAILED, f"{path}: {e}",
                          advice="fix ownership/permissions (reference "
                                 "RamDiskMountPrivilegeValidationTask)")
    free = shutil.disk_usage(path).free
    if free < min_free_bytes:
        return TaskResult(name, WARNING,
                          f"{path}: only {free >> 20} MiB free",
                          advice="quota exceeds the free space")
    return TaskResult(name, OK, f"{path}: writable, "
                                f"{free >> 20} MiB free")


def _check_native(name: str) -> TaskResult:
    from alluxio_tpu import native

    handle = native.lib()
    if handle is None:
        return TaskResult(name, WARNING,
                          "native framing library unavailable "
                          "(falls back to pure python)",
                          advice="install g++ or ship the prebuilt "
                                 ".so to enable the native scanner")
    return TaskResult(name, OK, "native framing library loads")


def _check_ssh(name: str, conf_dir: str, role_file: str) -> TaskResult:
    path = os.path.join(conf_dir, role_file)
    if not os.path.isfile(path):
        return TaskResult(name, SKIPPED, f"{path} absent")
    with open(path) as f:
        hosts = [ln.strip() for ln in f
                 if ln.strip() and not ln.startswith("#")]
    remote = [h for h in hosts if h not in ("localhost", "127.0.0.1")]
    # concurrent probes: serial 5s timeouts would make a pod-scale
    # role file take minutes
    procs = {h: subprocess.Popen(
        ["ssh", "-o", "BatchMode=yes", "-o", "ConnectTimeout=5",
         h, "true"], stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL) for h in remote}
    bad = [h for h, p in procs.items() if p.wait() != 0]
    if bad:
        return TaskResult(name, FAILED,
                          f"unreachable over ssh: {', '.join(bad)}",
                          advice="set up passwordless ssh (reference "
                                 "SshValidationTask)")
    return TaskResult(
        name, OK,
        f"{len(remote)} remote host(s) reachable"
        + (f", {len(hosts) - len(remote)} local" if len(hosts)
           != len(remote) else ""))


def _master_address(conf: Configuration) -> str:
    host = conf.get(Keys.MASTER_HOSTNAME) or "localhost"
    return f"{host}:{conf.get_int(Keys.MASTER_RPC_PORT)}"


def _check_cluster_conf(name: str, conf: Configuration) -> TaskResult:
    from alluxio_tpu.rpc.clients import MetaMasterClient

    try:
        report = MetaMasterClient(
            _master_address(conf)).get_config_report()
    except Exception as e:  # noqa: BLE001
        return TaskResult(name, SKIPPED,
                          f"master unreachable ({type(e).__name__}) — "
                          "run against a live cluster for the "
                          "consistency report")
    errs = report.get("errors") or []
    warns = report.get("warns") or []
    if errs:
        return TaskResult(name, FAILED, f"{len(errs)} inconsistent "
                          f"key(s): {errs[:3]}")
    if warns:
        return TaskResult(name, WARNING, f"{len(warns)} warning(s)")
    return TaskResult(name, OK, "cluster config consistent")


def env_tool(conf: Configuration,
             conf_dir: Optional[str] = None) -> ValidationTool:
    tool = ValidationTool("validateEnv")
    host = conf.get(Keys.MASTER_HOSTNAME) or "localhost"
    tool.add("master.rpc.port", lambda: _check_port(
        "master.rpc.port", host, conf.get_int(Keys.MASTER_RPC_PORT)))
    tool.add("master.web.port", lambda: _check_port(
        "master.web.port", host, conf.get_int(Keys.MASTER_WEB_PORT)))
    tool.add("worker.rpc.port", lambda: _check_port(
        "worker.rpc.port", "localhost",
        conf.get_int(Keys.WORKER_RPC_PORT)))
    levels = conf.get_int(Keys.WORKER_TIERED_STORE_LEVELS)
    for lvl in range(levels):
        key = Templates.WORKER_TIER_DIRS_PATH.format(lvl)
        paths = conf.get_list(key) or [""]
        for p in paths:
            tool.add(f"tier{lvl}.dir", lambda p=p, lvl=lvl: _check_dir(
                f"tier{lvl}.dir", p.strip(), 64 << 20))
    tool.add("native.lib", lambda: _check_native("native.lib"))
    cdir = conf_dir or os.environ.get("ATPU_CONF_DIR", "conf")
    tool.add("ssh.masters", lambda: _check_ssh(
        "ssh.masters", cdir, "masters"))
    tool.add("ssh.workers", lambda: _check_ssh(
        "ssh.workers", cdir, "workers"))
    tool.add("cluster.conf", lambda: _check_cluster_conf(
        "cluster.conf", conf))
    return tool


# -- hms tasks (reference HmsValidationTool tasks) --------------------

def hms_tool(connection: str, db_name: str = "default",
             tables: str = "", fs=None,
             timeout_s: float = 10.0) -> ValidationTool:
    from alluxio_tpu.table.hive import (
        HiveMetastoreClient, PathTranslator, mount_translations,
        parse_thrift_uri,
    )

    tool = ValidationTool("validateHms")
    state = {}

    def uri_check() -> TaskResult:
        try:
            state["addr"] = parse_thrift_uri(connection)
        except Exception as e:  # noqa: BLE001
            return TaskResult("hms.uri", FAILED, str(e),
                              advice="expected thrift://host:port "
                                     "(reference UriCheckTask)")
        return TaskResult("hms.uri", OK,
                          "thrift://%s:%d" % state["addr"])

    def connect() -> TaskResult:
        if "addr" not in state:
            return TaskResult("hms.connect", SKIPPED, "bad uri")
        host, port = state["addr"]
        try:
            with socket.create_connection((host, port),
                                          timeout=timeout_s):
                pass
        except OSError as e:
            return TaskResult("hms.connect", FAILED, str(e),
                              advice="metastore unreachable; check "
                                     "host/port/firewall (reference "
                                     "CreateHmsClientValidationTask)")
        state["connected"] = True
        return TaskResult("hms.connect", OK, f"{host}:{port} accepts")

    def metastore() -> TaskResult:
        if not state.get("connected"):
            return TaskResult("hms.metastore", SKIPPED,
                              "connect task did not pass")
        host, port = state["addr"]
        with HiveMetastoreClient(host, port,
                                 timeout_s=timeout_s) as cli:
            dbs = cli.get_all_databases()
        state["dbs"] = dbs
        return TaskResult("hms.metastore", OK,
                          f"{len(dbs)} database(s) visible")

    def database() -> TaskResult:
        if "dbs" not in state:
            return TaskResult("hms.database", SKIPPED,
                              "metastore task did not pass")
        if db_name not in state["dbs"]:
            return TaskResult("hms.database", FAILED,
                              f"database {db_name!r} not found "
                              f"(visible: {state['dbs'][:5]})")
        host, port = state["addr"]
        with HiveMetastoreClient(host, port,
                                 timeout_s=timeout_s) as cli:
            state["db"] = cli.get_database(db_name)
        return TaskResult("hms.database", OK, f"{db_name} readable")

    def table_check() -> TaskResult:
        if "db" not in state:
            return TaskResult("hms.tables", SKIPPED,
                              "database task did not pass")
        if not tables:
            return TaskResult("hms.tables", SKIPPED,
                              "no tables given (-t a,b)")
        host, port = state["addr"]
        translator = None
        if fs is not None:
            translator = PathTranslator(mount_translations(fs))
        bad, checked = [], 0
        with HiveMetastoreClient(host, port,
                                 timeout_s=timeout_s) as cli:
            for t in [t.strip() for t in tables.split(",") if t.strip()]:
                checked += 1
                try:
                    tbl = cli.get_table(db_name, t)
                except Exception as e:  # noqa: BLE001
                    bad.append(f"{t}: {type(e).__name__}")
                    continue
                # raw thrift struct: field 7 = StorageDescriptor,
                # whose field 2 = location (hive_metastore.thrift)
                loc = (tbl.get(7) or {}).get(2) or ""
                if translator is not None and loc and \
                        translator.translate(loc) is None:
                    bad.append(f"{t}: location {loc} not under any "
                               f"mount")
        if bad:
            return TaskResult("hms.tables", FAILED, "; ".join(bad),
                              advice="mount the table's UFS location "
                                     "(reference TableValidationTask)")
        return TaskResult("hms.tables", OK, f"{checked} table(s) ok")

    tool.add("hms.uri", uri_check)
    tool.add("hms.connect", connect)
    tool.add("hms.metastore", metastore)
    tool.add("hms.database", database)
    tool.add("hms.tables", table_check)
    return tool


# -- CLI --------------------------------------------------------------

def print_results(tool_name: str, results: List[TaskResult],
                  out=None) -> int:
    import sys

    out = out or sys.stdout
    worst = 0
    for r in results:
        line = f"[{r.state:>7}] {r.name}: {r.message}"
        if r.advice:
            line += f"\n          advice: {r.advice}"
        print(line, file=out)
        worst = max(worst, {OK: 0, SKIPPED: 0,
                            WARNING: 0, FAILED: 1}[r.state])
    n_fail = sum(1 for r in results if r.state == FAILED)
    print(f"{tool_name}: {len(results)} task(s), {n_fail} failed",
          file=out)
    return worst


def main_env(argv=None, conf: Optional[Configuration] = None,
             out=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="alluxio-tpu validateEnv")
    ap.add_argument("--conf-dir", default=None)
    args = ap.parse_args(argv)
    conf = conf or Configuration()
    tool = env_tool(conf, conf_dir=args.conf_dir)
    return print_results(tool.name, tool.run_all(), out=out)


def main_hms(argv=None, conf: Optional[Configuration] = None,
             out=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(prog="alluxio-tpu validateHms")
    ap.add_argument("-m", "--metastore", required=True,
                    help="thrift://host:port")
    ap.add_argument("-d", "--database", default="default")
    ap.add_argument("-t", "--tables", default="",
                    help="comma-separated table names to check")
    ap.add_argument("--no-fs", action="store_true",
                    help="skip mount-table location translation")
    args = ap.parse_args(argv)
    fs = None
    if not args.no_fs:
        try:
            from alluxio_tpu.client.file_system import FileSystem

            c = conf or Configuration()
            fs = FileSystem(_master_address(c), conf=c)
            fs.list_status("/")  # probe: fall back to no-fs when down
        except Exception:  # noqa: BLE001 cluster optional
            fs = None
    tool = hms_tool(args.metastore, db_name=args.database,
                    tables=args.tables, fs=fs)
    try:
        return print_results(tool.name, tool.run_all(), out=out)
    finally:
        if fs is not None:
            fs.close()
