"""runOperation: perform one metadata/IO operation N times over T
threads against a live cluster.

Env-adapted analogue of the reference's ``shell/.../cli/
RunOperation.java:37`` (ops CreateFile / CreateEmptyFile /
CreateAndDeleteEmptyFile / ListStatus; ``-n`` total across threads,
``-t`` threads, ``-d`` base dir, ``-s`` file size): the quick
sanity/smoke loop operators run before reaching for the full stress
suite.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import List, Optional

from alluxio_tpu.conf import Configuration, Keys

OPERATIONS = ("CreateFile", "CreateEmptyFile",
              "CreateAndDeleteEmptyFile", "ListStatus")


def _worker(fs, op: str, base: str, size: int, counter, times: int,
            thread_id: int, errors: List[str]) -> None:
    data = b"\x5a" * size
    while True:
        # itertools.count.__next__ is atomic in CPython — safe to share
        n = next(counter)
        if n >= times:
            return
        path = f"{base}/op-{thread_id}-{n}"
        try:
            if op == "CreateFile":
                fs.write_all(path, data)
            elif op == "CreateEmptyFile":
                fs.write_all(path, b"")
            elif op == "CreateAndDeleteEmptyFile":
                fs.write_all(path, b"")
                fs.delete(path)
            elif op == "ListStatus":
                fs.list_status(base)
        except Exception as e:  # noqa: BLE001 report, keep going
            errors.append(f"{path}: {type(e).__name__}: {e}")


def run(op: str, *, times: int = 1, threads: int = 1,
        directory: str = "/RunOperationDir", size: int = 4096,
        conf: Optional[Configuration] = None) -> dict:
    from alluxio_tpu.client.file_system import FileSystem

    if threads < 1:
        raise ValueError(f"threads must be >= 1, got {threads}")
    conf = conf or Configuration()
    host = conf.get(Keys.MASTER_HOSTNAME) or "localhost"
    address = f"{host}:{conf.get_int(Keys.MASTER_RPC_PORT)}"

    shared = itertools.count()
    errors: List[str] = []
    # one client per thread: mirrors real concurrent-client load and
    # avoids serializing on one connection
    clients = [FileSystem(address, conf=conf) for _ in range(threads)]
    try:
        clients[0].create_directory(directory, allow_exists=True)
        ts = [threading.Thread(
            target=_worker,
            args=(clients[i], op, directory, size, shared, times, i,
                  errors),
            name=f"run-operation-{i}") for i in range(threads)]
        t0 = time.monotonic()
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        wall = time.monotonic() - t0
    finally:
        for c in clients:
            c.close()
    done = times - len(errors)
    return {"operation": op, "requested": times, "succeeded": done,
            "errors": errors[:10], "error_count": len(errors),
            "seconds": round(wall, 3),
            "ops_per_s": round(done / wall, 1) if wall > 0 else 0.0}


def main(argv=None, conf: Optional[Configuration] = None,
         out=None) -> int:
    import argparse
    import sys

    out = out or sys.stdout
    ap = argparse.ArgumentParser(prog="alluxio-tpu runOperation")
    ap.add_argument("-op", "--operation", required=True,
                    choices=OPERATIONS)
    ap.add_argument("-n", "--num", type=int, default=1,
                    help="total operations across all threads")
    ap.add_argument("-t", "--threads", type=int, default=1)
    ap.add_argument("-d", "--dir", default="/RunOperationDir")
    ap.add_argument("-s", "--size", type=int, default=4096)
    args = ap.parse_args(argv)
    try:
        result = run(args.operation, times=args.num,
                     threads=args.threads, directory=args.dir,
                     size=args.size, conf=conf)
    except ValueError as e:
        print(f"runOperation: {e}", file=out)
        return 2
    for e in result["errors"]:
        print(f"error: {e}", file=out)
    print(f"{result['operation']}: {result['succeeded']}/"
          f"{result['requested']} ok in {result['seconds']}s "
          f"({result['ops_per_s']} op/s)", file=out)
    return 0 if result["error_count"] == 0 else 1
