"""Command-line shells.

Re-design of the reference's CLI layer (``shell/src/main/java/alluxio/cli``):
``fs`` (FileSystemShell, ~45 commands), ``fsadmin`` (FileSystemAdminShell),
``job`` (JobShell), plus ``format``. Dispatch lives in
``alluxio_tpu.shell.main`` (the ``bin/alluxio`` equivalent).
"""

from alluxio_tpu.shell.command import Command, CommandError, ShellContext

__all__ = ["Command", "CommandError", "ShellContext"]
