"""FileSystemShell: ``alluxio-tpu fs <command>``.

Re-design of ``shell/src/main/java/alluxio/cli/fs/FileSystemShell.java`` +
``fs/command/*.java`` — the ~40 user-facing filesystem commands mapped onto
the TPU-native client stack. Distributed variants submit job-service plans
(reference: ``DistributedLoadCommand.java`` et al.).
"""

from __future__ import annotations

import hashlib
import os
import sys
import time

from alluxio_tpu.shell.command import (
    Command, CommandError, Shell, expand_globs, format_ls_line, human_size,
)
from alluxio_tpu.utils.exceptions import NotFoundError
from alluxio_tpu.utils.uri import AlluxioURI

FS_SHELL = Shell("fs", "Interact with the alluxio-tpu file system.")


def _each(fs, args_paths):
    for raw in args_paths:
        for p in expand_globs(fs, raw):
            yield p


_PUMP_CHUNK = 4 << 20


def _pump(fin, fout) -> None:
    """Stream fin -> fout in chunks (both alluxio and local file objects)."""
    while True:
        chunk = fin.read(_PUMP_CHUNK)
        if not chunk:
            break
        fout.write(chunk)


def _walk_files(fs, path):
    """Yield FileInfo of every file under path (path itself if a file)."""
    info = fs.get_status(path)
    if not info.folder:
        yield info
        return
    for i in fs.list_status(path, recursive=True):
        if not i.folder:
            yield i


@FS_SHELL.register
class CatCommand(Command):
    name, description = "cat", "Print the file's contents to stdout."

    def configure(self, p):
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        for p in _each(fs, args.paths):
            with fs.open_file(p) as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    ctx.out.write(chunk.decode("utf-8", "replace"))
        return 0


@FS_SHELL.register
class HeadCommand(Command):
    name, description = "head", "Print the first bytes of a file."

    def configure(self, p):
        p.add_argument("-c", type=int, default=1024, dest="num_bytes")
        p.add_argument("path")

    def run(self, args, ctx):
        fs = ctx.fs()
        with fs.open_file(args.path) as f:
            ctx.out.write(f.read(args.num_bytes).decode("utf-8", "replace"))
        return 0


@FS_SHELL.register
class TailCommand(Command):
    name, description = "tail", "Print the last bytes of a file."

    def configure(self, p):
        p.add_argument("-c", type=int, default=1024, dest="num_bytes")
        p.add_argument("path")

    def run(self, args, ctx):
        fs = ctx.fs()
        info = fs.get_status(args.path)
        with fs.open_file(args.path) as f:
            start = max(0, info.length - args.num_bytes)
            ctx.out.write(f.pread(start, info.length - start)
                          .decode("utf-8", "replace"))
        return 0


@FS_SHELL.register
class LsCommand(Command):
    name, description = "ls", "List the directory's (or file's) status."

    def configure(self, p):
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("-h", action="store_true", dest="human")
        p.add_argument("--sort", default="path",
                       choices=["path", "size", "lastModificationTime"])
        p.add_argument("-r", action="store_true", dest="reverse")
        p.add_argument("-f", action="store_true", dest="force_sync",
                       help="force a metadata sync against the UFS")
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        key = {"path": lambda i: i.path, "size": lambda i: i.length,
               "lastModificationTime":
               lambda i: i.last_modification_time_ms}[args.sort]
        for p in _each(fs, args.paths):
            if args.force_sync:
                fs.fs_master.sync_metadata(p)
            info = fs.get_status(p)
            infos = [info] if not info.folder else fs.list_status(
                p, recursive=args.recursive)
            for i in sorted(infos, key=key, reverse=args.reverse):
                ctx.print(format_ls_line(i, human=args.human))
        return 0


@FS_SHELL.register
class MkdirCommand(Command):
    name, description = "mkdir", "Create directories (with parents)."

    def configure(self, p):
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        for p in args.paths:
            fs.create_directory(p, recursive=True)
            ctx.print(f"Successfully created directory {p}")
        return 0


@FS_SHELL.register
class TouchCommand(Command):
    name, description = "touch", "Create an empty file."

    def configure(self, p):
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        for p in args.paths:
            fs.write_all(p, b"")
            ctx.print(f"{p} has been created")
        return 0


@FS_SHELL.register
class RmCommand(Command):
    name, description = "rm", "Remove files or directories."

    def configure(self, p):
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("--alluxioOnly", action="store_true",
                       dest="alluxio_only",
                       help="remove only from the cache namespace, not UFS")
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        for p in _each(fs, args.paths):
            fs.delete(p, recursive=args.recursive,
                      alluxio_only=args.alluxio_only)
            ctx.print(f"{p} has been removed")
        return 0


@FS_SHELL.register
class MvCommand(Command):
    name, description = "mv", "Rename a file or directory."

    def configure(self, p):
        p.add_argument("src")
        p.add_argument("dst")

    def run(self, args, ctx):
        ctx.fs().rename(args.src, args.dst)
        ctx.print(f"Renamed {args.src} to {args.dst}")
        return 0


def _resolve_into_dir(fs, src: str, dst: str) -> str:
    """cp semantics: copying INTO an existing directory targets
    dst/<basename(src)>."""
    if fs.exists(dst) and fs.get_status(dst).folder:
        return AlluxioURI(dst).join(AlluxioURI(src).name).path
    return dst


def _copy_tree(fs, src: str, dst: str, ctx) -> None:
    info = fs.get_status(src)
    if info.folder:
        fs.create_directory(dst, recursive=True, allow_exists=True)
        for child in fs.list_status(src):
            _copy_tree(fs, child.path,
                       AlluxioURI(dst).join(child.name).path, ctx)
        return
    with fs.open_file(src) as fin, fs.create_file(dst) as fout:
        _pump(fin, fout)
    ctx.print(f"Copied {src} to {dst}")


@FS_SHELL.register
class CpCommand(Command):
    name = "cp"
    description = "Copy within the namespace, or from/to file:// paths."

    def configure(self, p):
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("src")
        p.add_argument("dst")

    def run(self, args, ctx):
        fs = ctx.fs()
        src_local = args.src.startswith("file://")
        dst_local = args.dst.startswith("file://")
        if src_local and dst_local:
            raise CommandError("use the system cp for local-to-local copies")
        if src_local:
            _from_local(fs, args.src[len("file://"):], args.dst, ctx)
        elif dst_local:
            _to_local(fs, args.src, args.dst[len("file://"):], ctx)
        else:
            matches = expand_globs(fs, args.src)
            if len(matches) > 1 and not (
                    fs.exists(args.dst) and fs.get_status(args.dst).folder):
                raise CommandError(
                    f"target {args.dst} must be an existing directory when "
                    f"copying multiple sources")
            for p in matches:
                info = fs.get_status(p)
                if info.folder and not args.recursive:
                    raise CommandError(f"{p} is a directory (use -R)")
                _copy_tree(fs, p, _resolve_into_dir(fs, p, args.dst), ctx)
        return 0


def _from_local(fs, local: str, remote: str, ctx) -> None:
    if os.path.isdir(local):
        fs.create_directory(remote, recursive=True, allow_exists=True)
        for name in sorted(os.listdir(local)):
            _from_local(fs, os.path.join(local, name),
                        AlluxioURI(remote).join(name).path, ctx)
        return
    if fs.exists(remote) and fs.get_status(remote).folder:
        remote = AlluxioURI(remote).join(os.path.basename(local)).path
    with open(local, "rb") as fin, fs.create_file(remote) as fout:
        _pump(fin, fout)
    ctx.print(f"Copied file://{local} to {remote}")


def _to_local(fs, remote: str, local: str, ctx) -> None:
    info = fs.get_status(remote)
    if info.folder:
        os.makedirs(local, exist_ok=True)
        for child in fs.list_status(remote):
            _to_local(fs, child.path, os.path.join(local, child.name), ctx)
        return
    if os.path.isdir(local):
        local = os.path.join(local, AlluxioURI(remote).name)
    with fs.open_file(remote) as fin, open(local, "wb") as fout:
        _pump(fin, fout)
    ctx.print(f"Copied {remote} to file://{local}")


@FS_SHELL.register
class CopyFromLocalCommand(Command):
    name = "copyFromLocal"
    description = "Copy a local file/dir into the namespace."

    def configure(self, p):
        p.add_argument("src")
        p.add_argument("dst")

    def run(self, args, ctx):
        _from_local(ctx.fs(), args.src, args.dst, ctx)
        return 0


@FS_SHELL.register
class CopyToLocalCommand(Command):
    name = "copyToLocal"
    description = "Copy a file/dir out to the local filesystem."

    def configure(self, p):
        p.add_argument("src")
        p.add_argument("dst")

    def run(self, args, ctx):
        _to_local(ctx.fs(), args.src, args.dst, ctx)
        return 0


@FS_SHELL.register
class StatCommand(Command):
    name, description = "stat", "Display all metadata of a path."

    def configure(self, p):
        p.add_argument("-f", dest="fmt", default=None,
                       help="format string, e.g. %%z (size) %%u %%g %%Y")
        p.add_argument("path")

    def run(self, args, ctx):
        info = ctx.fs().get_status(args.path)
        if args.fmt:
            out = (args.fmt.replace("%z", str(info.length))
                   .replace("%u", info.owner).replace("%g", info.group)
                   .replace("%y", time.strftime(
                       "%Y-%m-%d %H:%M:%S", time.localtime(
                           info.last_modification_time_ms / 1000)))
                   .replace("%Y", str(info.last_modification_time_ms))
                   .replace("%b", str(len(info.block_ids))))
            ctx.print(out)
            return 0
        for k, v in sorted(info.to_wire().items()):
            ctx.print(f"{k}: {v}")
        return 0


@FS_SHELL.register
class TestCommand(Command):
    name, description = "test", "Test path properties; exit code is 0/1."

    def configure(self, p):
        p.add_argument("-d", action="store_true", dest="is_dir")
        p.add_argument("-f", action="store_true", dest="is_file")
        p.add_argument("-e", action="store_true", dest="exists")
        p.add_argument("-z", action="store_true", dest="zero_len")
        p.add_argument("-s", action="store_true", dest="non_empty_dir")
        p.add_argument("path")

    def run(self, args, ctx):
        fs = ctx.fs()
        try:
            info = fs.get_status(args.path)
        except NotFoundError:
            return 1 if (args.exists or args.is_dir or args.is_file
                         or args.zero_len or args.non_empty_dir) else 0
        if args.is_dir:
            return 0 if info.folder else 1
        if args.is_file:
            return 0 if not info.folder else 1
        if args.zero_len:
            return 0 if (not info.folder and info.length == 0) else 1
        if args.non_empty_dir:
            return 0 if (info.folder and fs.list_status(args.path)) else 1
        return 0


@FS_SHELL.register
class ChecksumCommand(Command):
    name, description = "checksum", "Print the md5 checksum of a file."

    def configure(self, p):
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        for p in _each(fs, args.paths):
            h = hashlib.md5()
            with fs.open_file(p) as f:
                while True:
                    chunk = f.read(4 << 20)
                    if not chunk:
                        break
                    h.update(chunk)
            ctx.print(f"md5sum of {p}: {h.hexdigest()}")
        return 0


@FS_SHELL.register
class CountCommand(Command):
    name = "count"
    description = "Count directories, files and total bytes under a path."

    def configure(self, p):
        p.add_argument("-h", action="store_true", dest="human")
        p.add_argument("path")

    def run(self, args, ctx):
        fs = ctx.fs()
        files = dirs = total = 0
        for i in fs.list_status(args.path, recursive=True):
            if i.folder:
                dirs += 1
            else:
                files += 1
                total += i.length
        size = human_size(total) if args.human else str(total)
        ctx.print(f"{'File Count':>12s} {'Folder Count':>12s} "
                  f"{'Total Bytes':>12s}")
        ctx.print(f"{files:>12d} {dirs:>12d} {size:>12s}")
        return 0


@FS_SHELL.register
class DuCommand(Command):
    name, description = "du", "Show disk usage of files under a path."

    def configure(self, p):
        p.add_argument("-s", action="store_true", dest="summary")
        p.add_argument("-h", action="store_true", dest="human")
        p.add_argument("--memory", action="store_true",
                       help="also show bytes held in worker memory")
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        fmt = human_size if args.human else str
        for p in _each(fs, args.paths):
            total = in_mem = 0
            for i in _walk_files(fs, p):
                size = i.length
                mem = size * i.in_memory_percentage // 100
                total += size
                in_mem += mem
                if not args.summary:
                    line = f"{fmt(size):>12s} "
                    if args.memory:
                        line += f"{fmt(mem):>12s} "
                    ctx.print(line + i.path)
            line = f"{fmt(total):>12s} "
            if args.memory:
                line += f"{fmt(in_mem):>12s} "
            ctx.print(line + p)
        return 0


@FS_SHELL.register
class PinCommand(Command):
    name = "pin"
    description = "Pin a path so its blocks are never evicted."

    def configure(self, p):
        p.add_argument("path")
        p.add_argument("media", nargs="*",
                       help="optional allowed medium types")

    def run(self, args, ctx):
        ctx.fs().set_attribute(args.path, pinned=True,
                               pinned_media=args.media or None)
        ctx.print(f"File {args.path} was successfully pinned")
        return 0


@FS_SHELL.register
class UnpinCommand(Command):
    name, description = "unpin", "Unpin a path."

    def configure(self, p):
        p.add_argument("path")

    def run(self, args, ctx):
        ctx.fs().set_attribute(args.path, pinned=False)
        ctx.print(f"File {args.path} was successfully unpinned")
        return 0


@FS_SHELL.register
class FreeCommand(Command):
    name = "free"
    description = "Evict a path's blocks from worker caches (data stays in UFS)."

    def configure(self, p):
        p.add_argument("-f", action="store_true", dest="forced",
                       help="free even pinned files")
        p.add_argument("path")

    def run(self, args, ctx):
        ctx.fs().free(args.path, recursive=True, forced=args.forced)
        ctx.print(f"{args.path} was successfully freed from memory")
        return 0


@FS_SHELL.register
class LoadCommand(Command):
    name = "load"
    description = "Read a path through the cache so it becomes resident."

    def configure(self, p):
        p.add_argument("--local", action="store_true",
                       help="pull the data to this client's nearest worker")
        p.add_argument("path")

    def run(self, args, ctx):
        fs = ctx.fs()
        for i in _walk_files(fs, args.path):
            with fs.open_file(i.path) as f:
                while f.read(8 << 20):
                    pass
        ctx.print(f"{args.path} loaded")
        return 0


@FS_SHELL.register
class PersistCommand(Command):
    name, description = "persist", "Persist a path to its under storage."

    def configure(self, p):
        p.add_argument("paths", nargs="+")

    def run(self, args, ctx):
        fs = ctx.fs()
        for raw in args.paths:
            for p in expand_globs(fs, raw):
                for i in _walk_files(fs, p):
                    if not i.persisted:
                        fs.persist_now(i.path)
                        ctx.print(f"persisted file {i.path}")
        return 0


@FS_SHELL.register
class SetTtlCommand(Command):
    name, description = "setTtl", "Set time-to-live on a path."

    def configure(self, p):
        p.add_argument("--action", default="DELETE",
                       choices=["DELETE", "FREE"])
        p.add_argument("path")
        p.add_argument("ttl_ms", type=int)

    def run(self, args, ctx):
        ctx.fs().set_attribute(args.path, ttl=args.ttl_ms,
                               ttl_action=args.action)
        ctx.print(f"TTL of path '{args.path}' was successfully set to "
                  f"{args.ttl_ms} milliseconds, with ttl action {args.action}")
        return 0


@FS_SHELL.register
class UnsetTtlCommand(Command):
    name, description = "unsetTtl", "Remove the TTL from a path."

    def configure(self, p):
        p.add_argument("path")

    def run(self, args, ctx):
        ctx.fs().set_attribute(args.path, ttl=-1)
        ctx.print(f"TTL of path '{args.path}' was successfully removed")
        return 0


@FS_SHELL.register
class SetReplicationCommand(Command):
    name, description = "setReplication", "Set replication min/max of a path."

    def configure(self, p):
        p.add_argument("--min", type=int, default=None, dest="rmin")
        p.add_argument("--max", type=int, default=None, dest="rmax")
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("path")

    def run(self, args, ctx):
        if args.rmin is None and args.rmax is None:
            raise CommandError("at least one of --min/--max is required")
        ctx.fs().set_attribute(args.path, replication_min=args.rmin,
                               replication_max=args.rmax,
                               recursive=args.recursive)
        ctx.print(f"Changed the replication level of {args.path}")
        return 0


@FS_SHELL.register
class ChmodCommand(Command):
    name, description = "chmod", "Change the permission mode of a path."

    def configure(self, p):
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("mode")
        p.add_argument("path")

    def run(self, args, ctx):
        try:
            mode = int(args.mode, 8)
        except ValueError:
            raise CommandError(f"invalid octal mode: {args.mode}")
        ctx.fs().set_attribute(args.path, mode=mode,
                               recursive=args.recursive)
        ctx.print(f"Changed permission of {args.path} to {args.mode}")
        return 0


@FS_SHELL.register
class ChownCommand(Command):
    name, description = "chown", "Change the owner (and group) of a path."

    def configure(self, p):
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("owner", help="owner or owner:group")
        p.add_argument("path")

    def run(self, args, ctx):
        owner, _, group = args.owner.partition(":")
        ctx.fs().set_attribute(args.path, owner=owner, group=group or None,
                               recursive=args.recursive)
        ctx.print(f"Changed owner of {args.path} to {args.owner}")
        return 0


@FS_SHELL.register
class ChgrpCommand(Command):
    name, description = "chgrp", "Change the group of a path."

    def configure(self, p):
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("group")
        p.add_argument("path")

    def run(self, args, ctx):
        ctx.fs().set_attribute(args.path, group=args.group,
                               recursive=args.recursive)
        ctx.print(f"Changed group of {args.path} to {args.group}")
        return 0


@FS_SHELL.register
class MountCommand(Command):
    name, description = "mount", "Mount a UFS uri into the namespace."

    def configure(self, p):
        p.add_argument("--readonly", action="store_true")
        p.add_argument("--shared", action="store_true")
        p.add_argument("-o", "--option", action="append", default=[],
                       help="key=value UFS property")
        p.add_argument("path", nargs="?")
        p.add_argument("ufs_uri", nargs="?")

    def run(self, args, ctx):
        fs = ctx.fs()
        if args.path is None:  # no args: print the mount table
            for m in fs.get_mount_points():
                ro = " [readonly]" if m.read_only else ""
                ctx.print(f"{m.ufs_uri:<40s} on {m.alluxio_path}{ro}")
            return 0
        if args.ufs_uri is None:
            raise CommandError("usage: mount [options] <path> <ufs-uri>")
        for o in args.option:
            if "=" not in o:
                raise CommandError(f"--option must be key=value, got {o!r}")
        props = dict(o.split("=", 1) for o in args.option)
        fs.mount(args.path, args.ufs_uri, read_only=args.readonly,
                 shared=args.shared, properties=props or None)
        ctx.print(f"Mounted {args.ufs_uri} at {args.path}")
        return 0


@FS_SHELL.register
class UnmountCommand(Command):
    name, description = "unmount", "Unmount a namespace path."

    def configure(self, p):
        p.add_argument("path")

    def run(self, args, ctx):
        ctx.fs().unmount(args.path)
        ctx.print(f"Unmounted {args.path}")
        return 0


@FS_SHELL.register
class LocationCommand(Command):
    name, description = "location", "Show which workers hold a file's blocks."

    def configure(self, p):
        p.add_argument("path")

    def run(self, args, ctx):
        fs = ctx.fs()
        infos = fs.fs_master.get_file_block_info_list(args.path)
        ctx.print(f"{args.path} with {len(infos)} blocks:")
        for fbi in infos:
            hosts = [f"{l.address.host}:{l.address.rpc_port}"
                     for l in fbi.block_info.locations] or ["<not cached>"]
            ctx.print(f"  block {fbi.block_info.block_id} "
                      f"(len {fbi.block_info.length}): {', '.join(hosts)}")
        return 0


@FS_SHELL.register
class GetCapacityBytesCommand(Command):
    name, description = "getCapacityBytes", "Total worker capacity in bytes."

    def run(self, args, ctx):
        cap = ctx.block_client().get_capacity()
        ctx.print(sum(cap["capacity"].values()))
        return 0


@FS_SHELL.register
class GetUsedBytesCommand(Command):
    name, description = "getUsedBytes", "Total used worker bytes."

    def run(self, args, ctx):
        cap = ctx.block_client().get_capacity()
        ctx.print(sum(cap["used"].values()))
        return 0


@FS_SHELL.register
class LeaderCommand(Command):
    name, description = "leader", "Print the primary master address."

    def run(self, args, ctx):
        ctx.meta_client().get_master_info()  # verifies it is serving
        ctx.print(ctx.master_address)
        return 0


@FS_SHELL.register
class MasterInfoCommand(Command):
    name, description = "masterInfo", "Print cluster/master information."

    def run(self, args, ctx):
        info = ctx.meta_client().get_master_info()
        for k in sorted(info):
            ctx.print(f"{k}: {info[k]}")
        return 0


@FS_SHELL.register
class SetfaclCommand(Command):
    name = "setfacl"
    description = "Set the ACL of a path (-m entries | -b to remove)."

    def configure(self, p):
        p.add_argument("-R", action="store_true", dest="recursive")
        p.add_argument("-d", action="store_true", dest="default",
                       help="operate on the default ACL (directories)")
        p.add_argument("-b", action="store_true", dest="remove_all",
                       help="remove the extended ACL")
        p.add_argument("-m", dest="entries", default=None,
                       help="comma-separated entries, e.g. user:alice:rwx")
        p.add_argument("path")

    def run(self, args, ctx):
        if args.remove_all:
            entries = []
        elif args.entries:
            entries = [e for e in args.entries.split(",") if e]
        else:
            raise CommandError("one of -m <entries> or -b is required")
        ctx.fs_client().set_acl(args.path, entries, default=args.default,
                                recursive=args.recursive)
        ctx.print(f"Modified ACL of {args.path}")
        return 0


@FS_SHELL.register
class GetfaclCommand(Command):
    name = "getfacl"
    description = "Show the ACL of a path."

    def configure(self, p):
        p.add_argument("path")

    def run(self, args, ctx):
        acl = ctx.fs_client().get_acl(args.path)
        ctx.print(f"# file: {args.path}")
        ctx.print(f"# owner: {acl['owner']}")
        ctx.print(f"# group: {acl['group']}")
        for e in acl["entries"]:
            ctx.print(e)
        for e in acl["default_entries"]:
            ctx.print(f"default:{e}" if not e.startswith("default:") else e)
        return 0


@FS_SHELL.register
class StartSyncCommand(Command):
    name = "startSync"
    description = "Register a path as an active sync point."

    def configure(self, p):
        p.add_argument("path")

    def run(self, args, ctx):
        ctx.fs_client().start_sync(args.path)
        ctx.print(f"Started automatic syncing of '{args.path}'")
        return 0


@FS_SHELL.register
class StopSyncCommand(Command):
    name = "stopSync"
    description = "Unregister an active sync point."

    def configure(self, p):
        p.add_argument("path")

    def run(self, args, ctx):
        ctx.fs_client().stop_sync(args.path)
        ctx.print(f"Stopped automatic syncing of '{args.path}'")
        return 0


@FS_SHELL.register
class GetSyncPathListCommand(Command):
    name = "getSyncPathList"
    description = "List the active sync points."

    def run(self, args, ctx):
        for p in ctx.fs_client().get_sync_path_list():
            ctx.print(p)
        return 0


def _run_distributed(ctx, config: dict, wait: bool) -> int:
    jc = ctx.job_client()
    job_id = jc.run(config)
    ctx.print(f"Submitted job {job_id}")
    if not wait:
        return 0
    info = jc.wait_for_job(job_id)
    ctx.print(f"Job {job_id} {info.status}"
              + (f": {info.error_message}" if info.error_message else ""))
    return 0 if info.status == "COMPLETED" else 1


@FS_SHELL.register
class DistributedLoadCommand(Command):
    name = "distributedLoad"
    description = "Cache a path onto workers via the job service."

    def configure(self, p):
        p.add_argument("--replication", type=int, default=1)
        p.add_argument("--no-wait", action="store_true", dest="no_wait")
        p.add_argument("path")

    def run(self, args, ctx):
        return _run_distributed(ctx, {
            "type": "load", "path": args.path,
            "replication": args.replication}, not args.no_wait)


@FS_SHELL.register
class DistributedCpCommand(Command):
    name = "distributedCp"
    description = "Copy a path via parallel job-service tasks."

    def configure(self, p):
        p.add_argument("--overwrite", action="store_true")
        p.add_argument("--no-wait", action="store_true", dest="no_wait")
        p.add_argument("src")
        p.add_argument("dst")

    def run(self, args, ctx):
        return _run_distributed(ctx, {
            "type": "migrate", "source": args.src, "destination": args.dst,
            "overwrite": args.overwrite}, not args.no_wait)


@FS_SHELL.register
class DistributedMvCommand(Command):
    name = "distributedMv"
    description = "Move a path via parallel job-service tasks."

    def configure(self, p):
        p.add_argument("--no-wait", action="store_true", dest="no_wait")
        p.add_argument("src")
        p.add_argument("dst")

    def run(self, args, ctx):
        return _run_distributed(ctx, {
            "type": "migrate", "source": args.src, "destination": args.dst,
            "overwrite": True, "delete_source": True}, not args.no_wait)


def main(argv=None) -> int:
    return FS_SHELL.run(sys.argv[1:] if argv is None else argv)
