"""validateConf: sanity-check configuration before a process boots.

Re-design of ``shell/src/main/java/alluxio/cli/ValidateConf.java``:
validates a raw site-properties FILE (``--site path``, default the
ATPU_SITE_PROPERTIES location) — the surface where misspelled keys and
unparseable values actually enter, since ``load_site_properties``
deliberately skips unknown keys at boot — plus semantic cross-checks on
the effective configuration. Exit 0 = clean, 1 = errors (warnings pass).
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.conf.property_key import REGISTRY


def validate_site_file(path: str) -> Tuple[List[str], List[str]]:
    """Check every key/value in a java-properties-style file."""
    errors: List[str] = []
    warns: List[str] = []
    with open(path) as f:
        for lineno, line in enumerate(f, start=1):
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            if "=" not in line:
                warns.append(f"{path}:{lineno}: not key=value: {line!r}")
                continue
            k, _, v = line.partition("=")
            k, v = k.strip(), v.strip()
            pk = REGISTRY.get(k)
            if pk is None:
                if REGISTRY.is_valid(k):
                    continue  # template instance (tier levels etc.)
                if k.startswith("atpu."):
                    errors.append(
                        f"{path}:{lineno}: unknown property {k!r} — "
                        "misspelled key? (boot silently ignores it)")
                else:
                    warns.append(
                        f"{path}:{lineno}: non-framework property "
                        f"{k!r} ignored")
                continue
            try:
                pk.parse(v)
            except Exception as e:  # noqa: BLE001 — report, keep going
                errors.append(f"{path}:{lineno}: {k}={v!r}: "
                              f"{type(e).__name__}: {e}")
    return errors, warns


def cross_checks(conf: Configuration) -> Tuple[List[str], List[str]]:
    """Semantic checks on the EFFECTIVE configuration."""
    errors: List[str] = []
    warns: List[str] = []
    lo = conf.get_ms(Keys.MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MIN)
    hi = conf.get_ms(Keys.MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MAX)
    if lo >= hi:
        errors.append("embedded journal election timeout min >= max "
                      f"({lo}ms >= {hi}ms)")
    hb = conf.get_ms(Keys.MASTER_EMBEDDED_JOURNAL_HEARTBEAT_INTERVAL)
    if hb * 2 > lo:
        warns.append(
            f"journal heartbeat interval {hb}ms is more than half the "
            f"minimum election timeout {lo}ms — spurious elections "
            "under load")
    if conf.get_bytes(Keys.WORKER_RAMDISK_SIZE) <= 0:
        errors.append("worker ramdisk (MEM tier) size must be positive")
    levels = conf.get_int(Keys.WORKER_TIERED_STORE_LEVELS)
    if not 1 <= levels <= 3:
        errors.append(f"tiered store levels must be 1..3, got {levels}")
    if conf.get(Keys.USER_FILE_WRITE_TYPE_DEFAULT) == "THROUGH":
        warns.append("default write type THROUGH keeps no cached copy — "
                     "every read goes to the UFS")
    return errors, warns


def validate(conf: Configuration,
             site_path: Optional[str] = None
             ) -> Tuple[List[str], List[str]]:
    errors: List[str] = []
    warns: List[str] = []
    if site_path and os.path.exists(site_path):
        e, w = validate_site_file(site_path)
        errors += e
        warns += w
    e, w = cross_checks(conf)
    return errors + e, warns + w


def main(argv=None, conf: Configuration = None, out=None) -> int:
    import argparse
    import sys

    ap = argparse.ArgumentParser(prog="alluxio-tpu validateConf")
    ap.add_argument("--site", default=None)
    args = ap.parse_args(argv or [])
    out = out or sys.stdout
    conf = conf or Configuration()
    site = args.site or os.environ.get(
        "ATPU_SITE_PROPERTIES", "/etc/alluxio_tpu/site.properties")
    errors, warns = validate(conf, site_path=site)
    if args.site and not os.path.exists(args.site):
        # an EXPLICIT path that doesn't exist must not silently report
        # clean — that is exactly the typo this tool exists to catch
        errors.append(f"--site {args.site}: file does not exist")
    for w in warns:
        print(f"WARN  {w}", file=out)
    for e in errors:
        print(f"ERROR {e}", file=out)
    print(f"validateConf: {len(errors)} error(s), {len(warns)} "
          f"warning(s)", file=out)
    return 0 if not errors else 1
