"""Foreground process launchers.

Re-design of the reference's role mains (``master/AlluxioMaster.java:35``,
``worker/AlluxioWorker.java:44``, ``master/AlluxioJobMasterProcess.java``,
``proxy/AlluxioProxy.java:37``) plus ``bin/alluxio-start.sh``'s
launch-process: build the process from global config, serve until
SIGINT/SIGTERM.
"""

from __future__ import annotations

import logging
import signal
import socket
import threading

from alluxio_tpu.conf import Configuration, Keys

LOG = logging.getLogger(__name__)


def _serve_until_signal(stop_fn, banner: str) -> int:
    done = threading.Event()

    def _handler(signum, frame):
        done.set()

    signal.signal(signal.SIGINT, _handler)
    signal.signal(signal.SIGTERM, _handler)
    LOG.info("%s", banner)
    print(banner, flush=True)
    done.wait()
    stop_fn()
    return 0


def _master_address(conf: Configuration) -> str:
    addresses = conf.get(Keys.MASTER_RPC_ADDRESSES)
    if addresses:
        return str(addresses)
    return (f"{conf.get(Keys.MASTER_HOSTNAME)}:"
            f"{conf.get_int(Keys.MASTER_RPC_PORT)}")


def launch_master(conf: Configuration) -> int:
    if conf.get_bool(Keys.MASTER_HA_ENABLED):
        from alluxio_tpu.master.process import FaultTolerantMasterProcess

        proc = FaultTolerantMasterProcess(conf)
        proc.start()
        banner = ("alluxio-tpu master started (HA): "
                  + ("serving" if proc.serving else "standby, tailing"))
        return _serve_until_signal(proc.stop, banner)
    from alluxio_tpu.master.process import MasterProcess

    proc = MasterProcess(conf)
    port = proc.start()
    return _serve_until_signal(
        proc.stop, f"alluxio-tpu master serving on port {port}")


def launch_worker(conf: Configuration) -> int:
    from alluxio_tpu.rpc.clients import (
        BlockMasterClient, FsMasterClient, MetaMasterClient,
    )
    from alluxio_tpu.rpc.core import RpcServer
    from alluxio_tpu.rpc.worker_service import worker_service
    from alluxio_tpu.worker.process import BlockWorker
    from alluxio_tpu.worker.ufs_manager import WorkerUfsManager

    master_addr = _master_address(conf)
    fs_client = FsMasterClient(master_addr)
    worker = BlockWorker(conf, BlockMasterClient(master_addr), fs_client,
                         meta_master_client=MetaMasterClient(master_addr))
    worker.ufs_manager = WorkerUfsManager(fs_client)
    from alluxio_tpu.security.authentication import worker_authenticator

    server = RpcServer(bind_host="0.0.0.0",
                       port=conf.get_int(Keys.WORKER_RPC_PORT),
                       authenticator=worker_authenticator(conf))
    server.add_service(worker_service(worker))
    port = server.start()
    worker.address.rpc_port = port
    worker.address.data_port = port
    worker.start()

    def stop():
        worker.stop()
        server.stop()

    return _serve_until_signal(
        stop, f"alluxio-tpu worker serving on port {port}")


def launch_job_master(conf: Configuration) -> int:
    from alluxio_tpu.job.process import JobMasterProcess

    master_addr = _master_address(conf)
    proc = JobMasterProcess(conf, master_addr)
    port = proc.start()
    return _serve_until_signal(
        proc.stop, f"alluxio-tpu job master serving on port {port}")


def launch_job_worker(conf: Configuration) -> int:
    from alluxio_tpu.job.process import make_job_worker

    master_addr = _master_address(conf)
    job_master_addr = (f"{conf.get(Keys.JOB_MASTER_HOSTNAME)}:"
                       f"{conf.get_int(Keys.JOB_MASTER_RPC_PORT)}")
    jw = make_job_worker(conf, job_master_addr, master_addr,
                         socket.gethostname())
    jw.start()
    return _serve_until_signal(jw.stop, "alluxio-tpu job worker running")


def launch_proxy(conf: Configuration) -> int:
    try:
        from alluxio_tpu.proxy.process import ProxyProcess
    except ImportError:
        print("proxy process is not available in this build")
        return 1
    proc = ProxyProcess(conf)
    port = proc.start()
    return _serve_until_signal(
        proc.stop, f"alluxio-tpu proxy serving on port {port}")


def launch_logserver(conf: Configuration) -> int:
    from alluxio_tpu.logserver import LogServerProcess

    proc = LogServerProcess(conf.get(Keys.LOGSERVER_LOGS_DIR),
                            port=conf.get_int(Keys.LOGSERVER_PORT),
                            bind_host=conf.get(Keys.LOGSERVER_BIND_HOST))
    port = proc.start()
    return _serve_until_signal(
        proc.stop, f"alluxio-tpu log server on port {port}")


def launch_fuse(conf: Configuration) -> int:
    from alluxio_tpu.client.file_system import FileSystem
    from alluxio_tpu.fuse.process import AlluxioFuseMount, fuse_available

    if not fuse_available():
        print("FUSE is unavailable (libfuse.so.2 or /dev/fuse missing)")
        return 1
    fs = FileSystem(_master_address(conf), conf=conf)
    mount = AlluxioFuseMount(
        fs, conf.get(Keys.FUSE_MOUNT_POINT),
        root=conf.get(Keys.FUSE_FS_ROOT),
        options=conf.get(Keys.FUSE_MOUNT_OPTIONS))
    mount.mount()

    def stop() -> None:
        mount.unmount()
        fs.close()

    return _serve_until_signal(
        stop, f"alluxio-tpu fuse mounted at {mount.mountpoint}")


def maybe_enable_remote_logging(conf: Configuration) -> None:
    """Every role calls this: ships records to the log server when
    atpu.logserver.hostname is configured."""
    host = conf.get(Keys.LOGSERVER_HOSTNAME)
    if host:
        from alluxio_tpu.logserver import enable_remote_logging

        enable_remote_logging(host, conf.get_int(Keys.LOGSERVER_PORT))


_LAUNCHERS = {
    "master": launch_master,
    "worker": launch_worker,
    "job-master": launch_job_master,
    "job-worker": launch_job_worker,
    "proxy": launch_proxy,
    "logserver": launch_logserver,
    "fuse": launch_fuse,
}


def launch_process(role: str, conf: Configuration) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    if role != "logserver":
        maybe_enable_remote_logging(conf)
    return _LAUNCHERS[role](conf)
