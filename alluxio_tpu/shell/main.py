"""Top-level CLI dispatch: ``python -m alluxio_tpu.shell.main <shell> ...``.

Re-design of ``bin/alluxio`` (the bash dispatcher): routes to the fs,
fsadmin, job shells, ``format``, and the process launchers. Generic
options: ``--master host:port``, ``--job-master host:port``,
``-D key=value`` config overrides.
"""

from __future__ import annotations

import sys
from typing import List

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.shell.command import ShellContext

USAGE = """\
Usage: alluxio-tpu [generic options] <command> [command args]

Commands:
  fs         file system user shell (ls/cat/cp/pin/...)
  fsadmin    administration shell (report/doctor/journal/...)
  job        job service shell (ls/stat/cancel)
  table      table/catalog shell (attachdb/ls/sync/transform)
  stress     stress benchmark suite (worker/master/prefetch/table/write)
  validateConf  sanity-check the effective configuration
  validateEnv   pre-flight node checks (ports/dirs/ssh/native/cluster)
  validateHms   validate a Hive metastore before table attachdb
  runOperation  run one fs operation N times over T threads
  journalCrashTest  crash-kill masters under load, verify replay
  format     format master journal / worker storage
  master     run a master process
  worker     run a worker process
  job-master run a job master process
  job-worker run a job worker process
  proxy      run the REST/S3 proxy process
  logserver  run the centralized log aggregation server
  fuse       mount the namespace via FUSE (POSIX view)
  version    print the version

Generic options:
  --master host:port      metadata master address
  --job-master host:port  job master address
  -D key=value            set a configuration property
"""


class GenericOptionError(Exception):
    """Bad generic option; message is the usage error."""


def _split_host_port(value: str, flag: str,
                     default_port: int) -> "tuple[str, int]":
    host, sep, port = value.rpartition(":")
    if not sep:
        return value, default_port
    if not port.isdigit():
        raise GenericOptionError(
            f"{flag} expects host:port, got {value!r}")
    return host or "localhost", int(port)


def _parse_generic(argv: List[str], conf: Configuration) -> List[str]:
    rest: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--master" and i + 1 < len(argv):
            host, port = _split_host_port(
                argv[i + 1], "--master", conf.get_int(Keys.MASTER_RPC_PORT))
            conf.set(Keys.MASTER_HOSTNAME, host)
            conf.set(Keys.MASTER_RPC_PORT, port)
            i += 2
        elif a == "--job-master" and i + 1 < len(argv):
            host, port = _split_host_port(
                argv[i + 1], "--job-master",
                conf.get_int(Keys.JOB_MASTER_RPC_PORT))
            conf.set(Keys.JOB_MASTER_HOSTNAME, host)
            conf.set(Keys.JOB_MASTER_RPC_PORT, port)
            i += 2
        elif a == "-D" and i + 1 < len(argv):
            k, _, v = argv[i + 1].partition("=")
            conf.set(k, v)
            i += 2
        else:
            rest.append(a)
            i += 1
    return rest


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    conf = Configuration()
    try:
        argv = _parse_generic(argv, conf)
    except GenericOptionError as e:
        print(str(e), file=sys.stderr)
        return 1
    if not argv or argv[0] in ("-h", "--help", "help"):
        print(USAGE)
        return 0
    cmd, rest = argv[0], argv[1:]
    ctx = ShellContext(conf)
    if cmd == "fs":
        from alluxio_tpu.shell.fs_shell import FS_SHELL

        return FS_SHELL.run(rest, ctx)
    if cmd == "fsadmin":
        from alluxio_tpu.shell.fsadmin_shell import ADMIN_SHELL

        return ADMIN_SHELL.run(rest, ctx)
    if cmd == "job":
        from alluxio_tpu.shell.job_shell import JOB_SHELL

        return JOB_SHELL.run(rest, ctx)
    if cmd == "table":
        from alluxio_tpu.shell.table_shell import TABLE_SHELL

        return TABLE_SHELL.run(rest, ctx)
    if cmd == "stress":
        from alluxio_tpu.stress.__main__ import main as stress_main

        return stress_main(rest)
    if cmd == "validateConf":
        from alluxio_tpu.shell.validate import main as validate_main

        return validate_main(rest, conf=conf)
    if cmd == "validateEnv":
        from alluxio_tpu.shell.validate_env import main_env

        return main_env(rest, conf=conf)
    if cmd == "validateHms":
        from alluxio_tpu.shell.validate_env import main_hms

        return main_hms(rest, conf=conf)
    if cmd == "runOperation":
        from alluxio_tpu.shell.run_operation import main as runop_main

        return runop_main(rest, conf=conf)
    if cmd == "journalCrashTest":
        from alluxio_tpu.shell.journal_crash import main as crash_main

        return crash_main(rest)
    if cmd == "format":
        from alluxio_tpu.shell.format import main as format_main

        return format_main(rest)
    if cmd == "version":
        import alluxio_tpu

        print(getattr(alluxio_tpu, "__version__", "0.1.0"))
        return 0
    if cmd in ("master", "worker", "job-master", "job-worker", "proxy",
               "logserver", "fuse"):
        from alluxio_tpu.shell.launch import launch_process

        return launch_process(cmd, conf)
    print(f"Unknown command: {cmd}", file=sys.stderr)
    print(USAGE, file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
