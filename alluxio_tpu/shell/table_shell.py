"""TableShell: ``alluxio-tpu table <command>``.

Re-design of ``table/shell/src/main/java/alluxio/cli/table/TableShell.java``
+ ``command/{AttachDatabaseCommand,DetachDatabaseCommand,ListDbCommand,
SyncDatabaseCommand,TransformTableCommand,TransformStatusCommand}.java``:
the human entry point to the catalog service.
"""

from __future__ import annotations

from alluxio_tpu.shell.command import Command, Shell

TABLE_SHELL = Shell("table", "Interact with the table (catalog) service.")


@TABLE_SHELL.register
class AttachDbCommand(Command):
    name = "attachdb"
    description = ("Attach an under-database to the catalog "
                   "(e.g. attachdb fs /warehouse/sales).")

    def configure(self, p):
        p.add_argument("udb_type",
                       help="under-database type ('fs' or 'hive')")
        p.add_argument("connection",
                       help="UDB connection (namespace path for 'fs', "
                            "thrift://host:port for 'hive')")
        p.add_argument("--db", default="",
                       help="catalog database name (default: derived; "
                            "required for hive)")
        p.add_argument("-o", "--option", action="append", default=[],
                       metavar="K=V",
                       help="UDB option (e.g. "
                            "path_translations=hdfs://nn/w=/mnt/w)")

    def run(self, args, ctx):
        options = {}
        for kv in args.option:
            k, _, v = kv.partition("=")
            options[k] = v
        name = ctx.table_client().attach_database(
            args.udb_type, args.connection, args.db, options=options)
        ctx.print(f"Attached database {name}")
        return 0


@TABLE_SHELL.register
class DetachDbCommand(Command):
    name, description = "detachdb", "Detach a database from the catalog."

    def configure(self, p):
        p.add_argument("db")

    def run(self, args, ctx):
        ctx.table_client().detach_database(args.db)
        ctx.print(f"Detached database {args.db}")
        return 0


@TABLE_SHELL.register
class LsCommand(Command):
    name = "ls"
    description = ("List databases; 'ls <db>' lists its tables; "
                   "'ls <db> <table>' shows schema + partitions.")

    def configure(self, p):
        p.add_argument("db", nargs="?")
        p.add_argument("table", nargs="?")

    def run(self, args, ctx):
        client = ctx.table_client()
        if args.db is None:
            for db in client.get_all_databases():
                ctx.print(db)
            return 0
        if args.table is None:
            for t in client.get_all_tables(args.db):
                ctx.print(t)
            return 0
        t = client.get_table(args.db, args.table)
        ctx.print(f"table: {t['name']}")
        ctx.print(f"location: {t['location']}")
        ctx.print("schema:")
        for col in t["schema"]:
            ctx.print(f"  {col['name']}: {col['type']}")
        if t.get("partition_keys"):
            ctx.print(f"partition keys: {', '.join(t['partition_keys'])}")
        ctx.print(f"partitions ({len(t['partitions'])}):")
        for part in t["partitions"]:
            ctx.print(f"  {part['spec'] or '(unpartitioned)'} -> "
                      f"{part['location']}")
        return 0


@TABLE_SHELL.register
class SyncCommand(Command):
    name, description = "sync", "Re-snapshot a database from its UDB."

    def configure(self, p):
        p.add_argument("db")

    def run(self, args, ctx):
        n = ctx.table_client().sync_database(args.db)
        ctx.print(f"Synced database {args.db}: {n} tables")
        return 0


@TABLE_SHELL.register
class TransformCommand(Command):
    name = "transform"
    description = "Kick a transform (compact) job on a table."

    def configure(self, p):
        p.add_argument("db")
        p.add_argument("table")
        p.add_argument("-d", "--definition", default="compact")
        p.add_argument("--num-files", type=int, default=1,
                       help="compacted files per partition")

    def run(self, args, ctx):
        job_id = ctx.table_client().transform_table(
            args.db, args.table, definition=args.definition,
            options={"num_files": args.num_files})
        ctx.print(f"Started transform job {job_id} on "
                  f"{args.db}.{args.table}")
        ctx.print(f"Track it with: alluxio-tpu table transformStatus "
                  f"{job_id}")
        return 0


@TABLE_SHELL.register
class TransformStatusCommand(Command):
    name, description = "transformStatus", "Show a transform job's status."

    def configure(self, p):
        p.add_argument("job_id", type=int)

    def run(self, args, ctx):
        info = ctx.table_client().transform_status(args.job_id)
        ctx.print(f"job id: {info['job_id']}")
        ctx.print(f"table: {info['db']}.{info['table']}")
        ctx.print(f"definition: {info['definition']}")
        ctx.print(f"status: {info['status']}")
        ctx.print(f"layout applied: {bool(info.get('applied'))}")
        if info.get("error"):
            ctx.print(f"error: {info['error']}")
        return 0
