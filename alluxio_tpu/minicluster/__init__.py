"""Test cluster harnesses (reference: ``minicluster/``)."""

from alluxio_tpu.minicluster.ha_cluster import (  # noqa: F401
    HaCluster, WriteLedger,
)
from alluxio_tpu.minicluster.local_cluster import LocalCluster  # noqa: F401
from alluxio_tpu.minicluster.multi_process import (  # noqa: F401
    MultiProcessCluster,
)
