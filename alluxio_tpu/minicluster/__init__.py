"""Test cluster harnesses (reference: ``minicluster/``)."""

from alluxio_tpu.minicluster.local_cluster import LocalCluster  # noqa: F401
