"""In-process HA test cluster: N fault-tolerant masters over the
EMBEDDED (Raft) journal + workers, with a chaos-action catalog.

The failover analogue of :mod:`local_cluster`: every master is a
:class:`FaultTolerantMasterProcess` with its own journal folder and a
fixed RPC port, quorum membership rides real gRPC, and workers/clients
get the full ``host:port,host:port,...`` master list so their failover
paths (leader-hint redirects, rotation, standby reads, heartbeat
re-registration) are exercised for real (reference:
``MultiProcessCluster.java:94`` runs the same drills as subprocesses;
in-process keeps the chaos deterministic and fast).

``chaos_actions()`` exposes the cluster to a
:class:`~alluxio_tpu.utils.faults.FaultPlan`: kill/restart a master,
freeze a standby's journal apply, partition a quorum member, fail
journal fsyncs, delay a member's elections.  :class:`WriteLedger`
carries the drill invariants — no acknowledged write lost, no standby
read staler than its advertised ``md_version`` (docs/ha.md).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Dict, List, Optional, Tuple

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.master.process import FaultTolerantMasterProcess
from alluxio_tpu.rpc.clients import (
    BlockMasterClient, FsMasterClient, MetaMasterClient,
)
from alluxio_tpu.rpc.core import RpcServer
from alluxio_tpu.rpc.worker_service import worker_service
from alluxio_tpu.utils import faults
from alluxio_tpu.utils.wire import TieredIdentity, WorkerNetAddress
from alluxio_tpu.worker.process import BlockWorker
from alluxio_tpu.worker.ufs_manager import WorkerUfsManager


def free_ports(n: int) -> List[int]:
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class WriteLedger:
    """Acked-write ledger for chaos invariants.

    ``record(path, stamp)`` is called ONLY after the cluster
    acknowledged the write (the create returned).  Two checkable
    invariants fall out:

    - **durability**: after any failover, every recorded path must
      still exist (``verify_durable``) — an acked write that vanished
      means the journal acked before quorum/fsync durability;
    - **staleness contract**: a standby response stamped ``v`` must
      contain every recorded path whose ack-time stamp is ``<= v``
      (``staleness_violations``) — i.e. a standby read is never staler
      than the ``md_version`` it advertises.
    """

    def __init__(self) -> None:
        self.entries: List[Tuple[str, Optional[int]]] = []

    def record(self, path: str, stamp: Optional[int] = None) -> None:
        self.entries.append((str(path), stamp))

    def verify_durable(self, fs_client: FsMasterClient) -> List[str]:
        """Paths the cluster acked but can no longer see (empty=pass)."""
        return [p for p, _ in self.entries if not fs_client.exists(p)]

    def staleness_violations(self, visible_paths, stamp: Optional[int]
                             ) -> List[str]:
        """Recorded paths whose ack stamp is <= the response stamp but
        which the stamped response does not contain (empty=pass)."""
        if stamp is None:
            return []
        visible = set(visible_paths)
        return [p for p, s in self.entries
                if s is not None and s <= stamp and p not in visible]


class _WorkerHandle:
    def __init__(self, worker: BlockWorker, server: RpcServer, port: int):
        self.worker = worker
        self.server = server
        self.port = port

    def stop(self) -> None:
        self.worker.stop()
        self.server.stop()


class HaCluster:
    """N-master EMBEDDED-journal HA cluster, in-process."""

    def __init__(self, base_dir: str, *, num_masters: int = 3,
                 num_workers: int = 0,
                 conf_overrides: Optional[Dict] = None,
                 worker_mem_bytes: int = 64 << 20,
                 election_timeout: Tuple[str, str] = ("1s", "2s"),
                 ) -> None:
        # election timeouts default well above the reference 300-600ms:
        # in-process quorums share one GIL with busy test clients, and
        # heartbeats starved past a tight timeout churn elections
        # (observed: term 15 before the drill even started)
        self._base = base_dir
        self.num_masters = num_masters
        self._num_workers = num_workers
        self._worker_mem = worker_mem_bytes
        self.rpc_ports = free_ports(num_masters)
        self.raft_ports = free_ports(num_masters)
        self.rpc_addresses = [f"localhost:{p}" for p in self.rpc_ports]
        self.raft_addresses = [f"127.0.0.1:{p}" for p in self.raft_ports]
        self._election_timeout = election_timeout
        self._overrides = dict(conf_overrides or {})
        self.masters: List[Optional[FaultTolerantMasterProcess]] = \
            [None] * num_masters
        self.workers: List[_WorkerHandle] = []

    # -- assembly ------------------------------------------------------------
    @property
    def master_addresses(self) -> str:
        return ",".join(self.rpc_addresses)

    def _conf_for(self, index: int) -> Configuration:
        c = Configuration(load_env=False)
        base = os.path.join(self._base, f"m{index}")
        c.set(Keys.HOME, base)
        c.set(Keys.MASTER_JOURNAL_FOLDER, os.path.join(base, "journal"))
        c.set(Keys.MASTER_JOURNAL_TYPE, "EMBEDDED")
        c.set(Keys.MASTER_HA_ENABLED, True)
        c.set(Keys.MASTER_RPC_PORT, self.rpc_ports[index])
        c.set(Keys.MASTER_RPC_ADDRESSES, self.master_addresses)
        c.set(Keys.MASTER_EMBEDDED_JOURNAL_ADDRESS,
              self.raft_addresses[index])
        c.set(Keys.MASTER_EMBEDDED_JOURNAL_ADDRESSES,
              ",".join(self.raft_addresses))
        c.set(Keys.MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MIN,
              self._election_timeout[0])
        c.set(Keys.MASTER_EMBEDDED_JOURNAL_ELECTION_TIMEOUT_MAX,
              self._election_timeout[1])
        c.set(Keys.MASTER_SAFEMODE_WAIT, "0s")
        c.set(Keys.MASTER_STANDBY_TAIL_INTERVAL, "100ms")
        c.set(Keys.MASTER_HA_PUBLISH_INTERVAL, "200ms")
        # same-host masters would collide on the conventional /tmp
        # fastpath socket; failover behavior under test is the gRPC path
        c.set(Keys.MASTER_FASTPATH_ENABLED, False)
        c.set(Keys.MASTER_WORKER_TIMEOUT, "10000min")
        for k, v in self._overrides.items():
            c.set(k, v)
        return c

    def _start_master(self, index: int) -> FaultTolerantMasterProcess:
        root_ufs = os.path.join(self._base, "underFSStorage")
        os.makedirs(root_ufs, exist_ok=True)
        m = FaultTolerantMasterProcess(self._conf_for(index),
                                       root_ufs_uri=root_ufs)
        m.start()
        self.masters[index] = m
        return m

    def start(self, *, leader_timeout_s: float = 30.0) -> "HaCluster":
        for i in range(self.num_masters):
            self._start_master(i)
        self.await_primary(timeout_s=leader_timeout_s)
        for i in range(self._num_workers):
            self._start_worker(i)
        return self

    def _start_worker(self, index: int) -> _WorkerHandle:
        wconf = self._conf_for(0).copy()
        wdir = os.path.join(self._base, f"worker{index}")
        wconf.set(Keys.WORKER_DATA_FOLDER, wdir)
        wconf.set(Keys.WORKER_SHM_DIR, os.path.join(wdir, "shm"))
        wconf.set(Keys.WORKER_RAMDISK_SIZE, self._worker_mem)
        wconf.set(Keys.WORKER_HOSTNAME, "localhost")
        wconf.set(Keys.WORKER_WEB_PORT, 0)
        wconf.set(Keys.WORKER_BLOCK_HEARTBEAT_INTERVAL, "200ms")
        addrs = self.master_addresses
        bm_client = BlockMasterClient(addrs, conf=wconf)
        fs_client = FsMasterClient(addrs, conf=wconf)
        address = WorkerNetAddress(
            host="localhost", rpc_port=0,
            shm_dir=os.path.join(wdir, "shm"),
            tiered_identity=TieredIdentity.from_spec(
                f"host=localhost-w{index},slice=slice0"))
        worker = BlockWorker(wconf, bm_client, fs_client,
                             ufs_manager=None, address=address,
                             meta_master_client=MetaMasterClient(
                                 addrs, conf=wconf))
        worker.ufs_manager = WorkerUfsManager(fs_client)
        from alluxio_tpu.security.authentication import worker_authenticator

        server = RpcServer(bind_host="127.0.0.1", port=0,
                           authenticator=worker_authenticator(wconf))
        server.add_service(worker_service(worker))
        port = server.start()
        worker.address.rpc_port = port
        worker.address.data_port = port
        # full heartbeats: failover re-registration rides the heartbeat
        # command channel, which is half the point of this cluster
        worker.start()
        handle = _WorkerHandle(worker, server, port)
        self.workers.append(handle)
        return handle

    # -- quorum introspection ------------------------------------------------
    def primary_index(self) -> Optional[int]:
        for i, m in enumerate(self.masters):
            if m is not None and m.serving:
                return i
        return None

    @property
    def primary(self) -> Optional[FaultTolerantMasterProcess]:
        i = self.primary_index()
        return self.masters[i] if i is not None else None

    def standby_indices(self) -> List[int]:
        return [i for i, m in enumerate(self.masters)
                if m is not None and not m.serving]

    def await_primary(self, timeout_s: float = 30.0) -> int:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            i = self.primary_index()
            if i is not None:
                return i
            time.sleep(0.05)
        raise AssertionError(
            f"no primary master within {timeout_s}s "
            f"(roles: {[m and m.serving for m in self.masters]})")

    # -- chaos actions (FaultPlan catalog) -----------------------------------
    def kill_master(self, index: int) -> str:
        m = self.masters[index]
        if m is not None:
            m.stop()
            self.masters[index] = None
        return f"killed m{index}"

    def kill_primary(self) -> str:
        i = self.primary_index()
        if i is None:
            raise AssertionError("no primary to kill")
        return self.kill_master(i)

    def restart_master(self, index: int) -> str:
        if self.masters[index] is not None:
            self.kill_master(index)
        self._start_master(index)
        return f"restarted m{index}"

    def freeze_tailer(self, index: int) -> str:
        """Freeze standby ``index``'s journal apply (Raft apply loop +
        tailer): its served md_version stops advancing."""
        faults.injector().set(
            tailer_freeze_scope=self.raft_addresses[index])
        return f"froze tailer of m{index}"

    def unfreeze_tailer(self) -> str:
        faults.injector().set(tailer_freeze_scope="")
        return "tailer thawed"

    def partition(self, index: int) -> str:
        """Cut quorum traffic to/from member ``index`` (client RPC stays
        reachable — the realistic control-plane partition)."""
        faults.injector().set(partitioned=[self.raft_addresses[index]])
        return f"partitioned m{index}"

    def heal_partition(self) -> str:
        faults.injector().set(partitioned=[])
        return "partition healed"

    def delay_elections(self, index: int) -> str:
        """Member ``index`` sits out elections (still votes)."""
        faults.injector().set(
            election_freeze_scope=self.raft_addresses[index])
        return f"elections delayed on m{index}"

    def release_elections(self) -> str:
        faults.injector().set(election_freeze_scope="")
        return "elections released"

    def fail_fsync(self, count: int = 1) -> str:
        """Arm the next ``count`` journal fsyncs to fail (LOCAL-journal
        flavor crash point; see docs/ha.md)."""
        faults.injector().set(fsync_errors=count)
        return f"armed {count} fsync failures"

    def chaos_actions(self) -> Dict:
        """The action catalog a :class:`FaultPlan` runs against."""
        return {
            "kill_primary": self.kill_primary,
            "kill_master": self.kill_master,
            "restart_master": self.restart_master,
            "freeze_tailer": self.freeze_tailer,
            "unfreeze_tailer": self.unfreeze_tailer,
            "partition": self.partition,
            "heal_partition": self.heal_partition,
            "delay_elections": self.delay_elections,
            "release_elections": self.release_elections,
            "fail_fsync": self.fail_fsync,
        }

    # -- clients -------------------------------------------------------------
    def fs_client(self, **kw) -> FsMasterClient:
        return FsMasterClient(self.master_addresses, **kw)

    def meta_client(self, **kw) -> MetaMasterClient:
        return MetaMasterClient(self.master_addresses, **kw)

    def block_client(self, **kw) -> BlockMasterClient:
        return BlockMasterClient(self.master_addresses, **kw)

    def file_system(self, **conf_overrides):
        from alluxio_tpu.client.file_system import FileSystem

        conf = self._conf_for(0).copy()
        for k, v in conf_overrides.items():
            conf.set(k, v)
        return FileSystem(self.master_addresses, conf=conf)

    # -- lifecycle -----------------------------------------------------------
    def stop(self) -> None:
        faults.injector().reset()
        for w in self.workers:
            w.stop()
        self.workers = []
        for i, m in enumerate(self.masters):
            if m is not None:
                m.stop()
                self.masters[i] = None

    def __enter__(self) -> "HaCluster":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
