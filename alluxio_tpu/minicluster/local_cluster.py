"""In-process test cluster: master + N workers over real gRPC.

Re-design of ``minicluster/.../LocalAlluxioCluster.java:45`` +
``LocalAlluxioClusterResource``: every role runs as threads in one process,
RPC rides real gRPC on ephemeral ports, tier dirs live under a scratch
directory. Functional tests use this; process-level failover tests use
``multi_process.py`` (reference: ``MultiProcessCluster.java:94``).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.master.process import MasterProcess
from alluxio_tpu.rpc.clients import (
    BlockMasterClient, FsMasterClient, MetaMasterClient, WorkerClient,
)
from alluxio_tpu.rpc.core import RpcServer
from alluxio_tpu.rpc.worker_service import worker_service
from alluxio_tpu.utils.wire import TieredIdentity, WorkerNetAddress
from alluxio_tpu.worker.process import BlockWorker
from alluxio_tpu.worker.ufs_manager import WorkerUfsManager


class _WorkerHandle:
    def __init__(self, worker: BlockWorker, server: RpcServer, port: int):
        self.worker = worker
        self.server = server
        self.port = port

    @property
    def address(self) -> str:
        return f"localhost:{self.port}"

    def stop(self) -> None:
        self.worker.stop()
        self.server.stop()


class LocalCluster:
    def __init__(self, base_dir: str, *, num_workers: int = 1,
                 conf_overrides: Optional[Dict] = None,
                 worker_mem_bytes: int = 64 << 20,
                 block_size: int = 1 << 20,
                 start_worker_heartbeats: bool = False,
                 start_job_service: bool = False) -> None:
        self._base = base_dir
        self._num_workers = num_workers
        self._worker_mem = worker_mem_bytes
        self._start_hb = start_worker_heartbeats
        self.conf = Configuration(load_env=False)
        self.conf.set(Keys.HOME, base_dir)
        self.conf.set(Keys.MASTER_JOURNAL_FOLDER,
                      os.path.join(base_dir, "journal"))
        self.conf.set(Keys.MASTER_RPC_PORT, 0)  # ephemeral
        self.conf.set(Keys.USER_BLOCK_SIZE_BYTES_DEFAULT, block_size)
        self.conf.set(Keys.MASTER_SAFEMODE_WAIT, "0s")
        if not start_worker_heartbeats:
            # No heartbeat loop means worker liveness is unknowable: the
            # lost-worker detector would silently expire a healthy worker
            # after the default timeout (and with no heartbeat to carry
            # the re-register command it can never come back). Overrides
            # below still win for tests that drive detection explicitly.
            self.conf.set(Keys.MASTER_WORKER_TIMEOUT, "10000min")
        for k, v in (conf_overrides or {}).items():
            self.conf.set(k, v)
        self.master: Optional[MasterProcess] = None
        self.workers: List[_WorkerHandle] = []
        self._start_job_service = start_job_service
        self.job_master = None
        self.job_workers: List = []

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> "LocalCluster":
        root_ufs = os.path.join(self._base, "underFSStorage")
        os.makedirs(root_ufs, exist_ok=True)
        self.master = MasterProcess(self.conf, root_ufs_uri=root_ufs)
        self.master.start()
        for i in range(self._num_workers):
            self._start_worker(i)
        if self._start_job_service:
            self.start_job_service()
        return self

    def _start_worker(self, index: int) -> _WorkerHandle:
        wconf = self.conf.copy()
        wdir = os.path.join(self._base, f"worker{index}")
        wconf.set(Keys.WORKER_DATA_FOLDER, wdir)
        wconf.set(Keys.WORKER_SHM_DIR, os.path.join(wdir, "shm"))
        wconf.set(Keys.WORKER_RAMDISK_SIZE, self._worker_mem)
        wconf.set(Keys.WORKER_HOSTNAME, "localhost")
        # ephemeral per-worker web port: a shared fixed default would
        # EADDRINUSE the second worker when the endpoint is enabled
        wconf.set(Keys.WORKER_WEB_PORT, 0)
        bm_client = BlockMasterClient(self.master.address)
        fs_client = FsMasterClient(self.master.address)
        # distinct locality hosts so policies can tell workers apart
        address = WorkerNetAddress(
            host="localhost", rpc_port=0,
            shm_dir=os.path.join(wdir, "shm"),
            tiered_identity=TieredIdentity.from_spec(
                f"host=localhost-w{index},slice=slice0"))
        worker = BlockWorker(wconf, bm_client, fs_client,
                             ufs_manager=None, address=address,
                             meta_master_client=MetaMasterClient(
                                 self.master.address))
        # UFS resolution must be in place before the RPC server serves a
        # single read (a UFS-descriptor read in the gap would crash on None)
        worker.ufs_manager = WorkerUfsManager(fs_client)
        from alluxio_tpu.security.authentication import worker_authenticator

        server = RpcServer(bind_host="127.0.0.1", port=0,
                           authenticator=worker_authenticator(wconf))
        server.add_service(worker_service(worker))
        port = server.start()
        worker.address.rpc_port = port
        worker.address.data_port = port
        if self._start_hb:
            worker.start()
        else:
            worker._master_sync.register_with_master()
            worker.maybe_start_web()
        handle = _WorkerHandle(worker, server, port)
        self.workers.append(handle)
        return handle

    def add_worker(self) -> _WorkerHandle:
        return self._start_worker(len(self.workers))

    def start_job_service(self) -> None:
        """Start a job master + one job worker per block worker
        (reference: job master/worker co-deployment, §3.5 of SURVEY.md)."""
        from alluxio_tpu.job.process import JobMasterProcess, make_job_worker

        jconf = self.conf.copy()
        jconf.set(Keys.JOB_MASTER_RPC_PORT, 0)
        # tight heartbeat so in-process tests converge fast
        jconf.set(Keys.JOB_WORKER_HEARTBEAT_INTERVAL, "50ms")
        self.job_master = JobMasterProcess(jconf, self.master.address)
        self.job_master.start()
        # the metadata master's table service reaches the job master via
        # its conf; propagate the ephemeral port it actually bound
        self.conf.set(Keys.JOB_MASTER_RPC_PORT,
                      int(self.job_master.address.rsplit(":", 1)[1]))
        for i in range(len(self.workers)):
            jw = make_job_worker(jconf, self.job_master.address,
                                 self.master.address, f"localhost-w{i}")
            jw.start()
            self.job_workers.append(jw)
        self.master.attach_replication_checker(self.job_client(),
                                               interval_s=0.1)
        self.master.attach_persistence_scheduler(self.job_client(),
                                                 interval_s=0.1)

    def stop(self) -> None:
        for jw in self.job_workers:
            jw.stop()
        if self.job_master is not None:
            self.job_master.stop()
        for w in self.workers:
            w.stop()
        if self.master is not None:
            self.master.stop()

    def __enter__(self) -> "LocalCluster":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- clients ------------------------------------------------------------
    def fs_client(self) -> FsMasterClient:
        return FsMasterClient(self.master.address)

    def block_client(self) -> BlockMasterClient:
        return BlockMasterClient(self.master.address)

    def meta_client(self) -> MetaMasterClient:
        return MetaMasterClient(self.master.address)

    def worker_client(self, index: int = 0) -> WorkerClient:
        return WorkerClient(self.workers[index].address)

    def job_client(self):
        from alluxio_tpu.rpc.job_service import JobMasterClient

        return JobMasterClient(self.job_master.address)

    def file_system(self):
        """A full FileSystem client bound to this cluster."""
        from alluxio_tpu.client.file_system import FileSystem

        return FileSystem(self.master.address, conf=self.conf)
