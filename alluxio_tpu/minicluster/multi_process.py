"""Multi-process test cluster: real OS processes per role.

Re-design of ``minicluster/src/main/java/alluxio/multi/process/
MultiProcessCluster.java:94`` (+ ``PortCoordination``): spawns each
master/worker as a separate ``python -m alluxio_tpu.shell.main <role>``
subprocess configured via ``ATPU_*`` env vars, with kill/restart of
individual processes for failover tests (the crash-recovery analogue of
``LimitedLifeMasterProcess``)."""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from alluxio_tpu.rpc.clients import FsMasterClient, MetaMasterClient
from alluxio_tpu.utils.exceptions import AlluxioTpuError


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ManagedProcess:
    """One spawned role process."""

    def __init__(self, role: str, env: Dict[str, str],
                 log_path: str) -> None:
        self.role = role
        self.env = env
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None

    def start(self) -> None:
        log = open(self.log_path, "ab")
        env = {**os.environ, **self.env, "JAX_PLATFORMS": "cpu"}
        # control-plane roles never touch the accelerator: drop the TPU
        # tunnel's site hook trigger so each subprocess skips its multi-
        # second jax/PJRT init (dominates boot latency on small boxes)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "alluxio_tpu.shell.main", self.role],
            env=env, stdout=log, stderr=subprocess.STDOUT)

    def kill(self, sig: int = signal.SIGKILL) -> None:
        """Hard-kill (crash simulation)."""
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(sig)
            self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait(timeout=5)

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class MultiProcessCluster:
    """N masters (shared journal dir -> file-lock election) + M workers,
    each a real subprocess."""

    def __init__(self, base_dir: str, *, num_masters: int = 1,
                 num_workers: int = 1,
                 journal_type: str = "LOCAL",
                 extra_conf: Optional[Dict[str, str]] = None) -> None:
        """``journal_type``: LOCAL = shared journal dir + flock election
        (masters must share a filesystem); EMBEDDED = per-master journal
        dirs + Raft quorum over the embedded journal ports (true
        multi-host HA; reference: EmbeddedJournalIntegrationTest)."""
        self.base = base_dir
        self.journal_dir = os.path.join(base_dir, "journal")
        self.journal_type = journal_type.upper()
        self.master_ports = [free_port() for _ in range(num_masters)]
        self.raft_ports = [free_port() for _ in range(num_masters)]
        self.worker_ports = [free_port() for _ in range(num_workers)]
        self.masters: List[ManagedProcess] = []
        self.workers: List[ManagedProcess] = []
        self._extra = dict(extra_conf or {})
        os.makedirs(self.journal_dir, exist_ok=True)
        os.makedirs(os.path.join(base_dir, "logs"), exist_ok=True)

    # -- addresses -----------------------------------------------------------
    @property
    def master_addresses(self) -> str:
        return ",".join(f"localhost:{p}" for p in self.master_ports)

    def _common_env(self) -> Dict[str, str]:
        env = {
            "ATPU_HOME": self.base,
            "ATPU_MASTER_JOURNAL_FOLDER": self.journal_dir,
            "ATPU_MASTER_HOSTNAME": "localhost",
            "ATPU_MASTER_SAFEMODE_WAIT": "0s",
        }
        for k, v in self._extra.items():
            env["ATPU_" + str(k).replace("atpu.", "").replace(".", "_")
                .upper()] = str(v)
        return env

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "MultiProcessCluster":
        for i, port in enumerate(self.master_ports):
            self.start_master(i)
        self.wait_for_primary()
        for i in range(len(self.worker_ports)):
            self.start_worker(i)
        self.wait_for_workers(len(self.worker_ports))
        return self

    @property
    def raft_addresses(self) -> str:
        return ",".join(f"127.0.0.1:{p}" for p in self.raft_ports)

    def start_master(self, index: int) -> ManagedProcess:
        env = self._common_env()
        env["ATPU_MASTER_RPC_PORT"] = str(self.master_ports[index])
        env["ATPU_MASTER_HA_ENABLED"] = "true"
        if self.journal_type == "EMBEDDED":
            env["ATPU_MASTER_JOURNAL_TYPE"] = "EMBEDDED"
            # each quorum member keeps its OWN journal (no shared fs)
            env["ATPU_MASTER_JOURNAL_FOLDER"] = os.path.join(
                self.base, f"journal-m{index}")
            env["ATPU_MASTER_EMBEDDED_JOURNAL_ADDRESSES"] = \
                self.raft_addresses
            env["ATPU_MASTER_EMBEDDED_JOURNAL_ADDRESS"] = \
                f"127.0.0.1:{self.raft_ports[index]}"
        p = ManagedProcess(
            "master", env,
            os.path.join(self.base, "logs", f"master{index}.out"))
        p.start()
        if index < len(self.masters):
            self.masters[index] = p
        else:
            self.masters.append(p)
        return p

    def start_worker(self, index: int) -> ManagedProcess:
        env = self._common_env()
        wdir = os.path.join(self.base, f"worker{index}")
        env.update({
            # HA: workers address the full master list and fail over
            "ATPU_MASTER_RPC_ADDRESSES": self.master_addresses,
            "ATPU_WORKER_RPC_PORT": str(self.worker_ports[index]),
            "ATPU_WORKER_DATA_FOLDER": wdir,
            "ATPU_WORKER_SHM_DIR": os.path.join(wdir, "shm"),
            "ATPU_WORKER_HOSTNAME": "localhost",
            "ATPU_WORKER_RAMDISK_SIZE": "64MB",
            "ATPU_TIERED_IDENTITY": f"host=localhost-w{index}",
            "ATPU_WORKER_BLOCK_HEARTBEAT_INTERVAL": "200ms",
        })
        p = ManagedProcess(
            "worker", env,
            os.path.join(self.base, "logs", f"worker{index}.out"))
        p.start()
        if index < len(self.workers):
            self.workers[index] = p
        else:
            self.workers.append(p)
        return p

    # -- readiness -----------------------------------------------------------
    def wait_for_primary(self, timeout_s: float = 180.0) -> str:
        """Block until some master serves RPCs; returns its address."""
        deadline = time.monotonic() + timeout_s
        last_err: Optional[Exception] = None
        while time.monotonic() < deadline:
            for port in self.master_ports:
                try:
                    MetaMasterClient(f"localhost:{port}",
                                     retry_duration_s=0.2).get_master_info()
                    return f"localhost:{port}"
                except (AlluxioTpuError, Exception) as e:  # noqa: BLE001
                    last_err = e
            time.sleep(0.2)
        raise TimeoutError(f"no primary master within {timeout_s}s: "
                           f"{last_err}")

    def primary_index(self, timeout_s: float = 180.0) -> int:
        """Index of the master currently serving RPCs (the address
        format and port list are this class's own invariants — callers
        must not re-parse them)."""
        addr = self.wait_for_primary(timeout_s)
        return self.master_ports.index(int(addr.rsplit(":", 1)[1]))

    def wait_for_workers(self, count: int, timeout_s: float = 60.0) -> None:
        from alluxio_tpu.rpc.clients import BlockMasterClient

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                c = BlockMasterClient(self.master_addresses,
                                      retry_duration_s=1.0)
                if len(c.get_worker_infos()) >= count:
                    return
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.2)
        raise TimeoutError(f"{count} workers not registered in {timeout_s}s")

    # -- clients -------------------------------------------------------------
    def fs_client(self) -> FsMasterClient:
        return FsMasterClient(self.master_addresses)

    def file_system(self):
        from alluxio_tpu.client.file_system import FileSystem
        from alluxio_tpu.conf import Configuration

        return FileSystem(self.master_addresses,
                          conf=Configuration(load_env=False))

    # -- teardown ------------------------------------------------------------
    def stop(self) -> None:
        for p in self.workers + self.masters:
            p.stop()

    def __enter__(self) -> "MultiProcessCluster":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
