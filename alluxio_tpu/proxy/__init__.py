"""REST/S3 proxy: S3-compatible HTTP access to the namespace."""

from alluxio_tpu.proxy.process import ProxyProcess

__all__ = ["ProxyProcess"]
