"""S3-compatible REST proxy over the FileSystem client.

Re-design of ``core/server/proxy/src/main/java/alluxio/proxy/
{AlluxioProxy.java:37,s3/S3RestServiceHandler.java:75}``: a standalone
process exposing buckets/objects over the S3 REST dialect so any S3
client/SDK (awscli, boto3, s3fs, spark-s3a) can read and write the
namespace. Buckets are the children of ``atpu.proxy.s3.root``; object
keys map to paths below their bucket.

Supported (the surface the reference handler implements):
  GET    /                      ListBuckets
  PUT    /{bucket}              CreateBucket
  DELETE /{bucket}              DeleteBucket (must be empty)
  GET    /{bucket}?list-type=2  ListObjectsV2 (prefix, delimiter,
                                max-keys, continuation via start-after)
  HEAD   /{bucket}/{key}        HeadObject
  GET    /{bucket}/{key}        GetObject (Range: bytes=a-b)
  PUT    /{bucket}/{key}        PutObject (and CopyObject via
                                x-amz-copy-source)
  DELETE /{bucket}/{key}        DeleteObject
  POST   /{bucket}/{key}?uploads                 CreateMultipartUpload
  PUT    /{bucket}/{key}?partNumber=N&uploadId=  UploadPart
  POST   /{bucket}/{key}?uploadId=               CompleteMultipartUpload
  DELETE /{bucket}/{key}?uploadId=               AbortMultipartUpload
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import parse_qs, unquote, urlsplit
from xml.sax.saxutils import escape

from alluxio_tpu.utils.exceptions import (
    DirectoryNotEmptyError, FileDoesNotExistError, InvalidArgumentError,
    InvalidPathError,
)

LOG = logging.getLogger(__name__)

_MULTIPART_DIR = "_atpu_multipart"


def _xml(body: str) -> bytes:
    return ('<?xml version="1.0" encoding="UTF-8"?>' + body).encode()


def _error(code: str, message: str, resource: str) -> bytes:
    return _xml(f"<Error><Code>{escape(code)}</Code>"
                f"<Message>{escape(message)}</Message>"
                f"<Resource>{escape(resource)}</Resource></Error>")


def _iso(ms: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ms / 1000))


class _S3State:
    """Shared across handler instances (one per request thread)."""

    def __init__(self, fs, root: str) -> None:
        self.fs = fs
        self.root = root.rstrip("/") or "/s3"
        #: uploadId -> (bucket, key); parts live in the namespace under
        #: root/_atpu_multipart/<uploadId>/ so aborted uploads are
        #: visible/sweepable, matching the reference's temp-dir scheme
        self.uploads: Dict[str, tuple] = {}
        self.lock = threading.Lock()


class ProxyProcess:
    """The proxy role (reference: ``AlluxioProxy.java:37``)."""

    def __init__(self, conf, fs=None) -> None:
        from alluxio_tpu.conf import Keys

        self._conf = conf
        self._owns_fs = fs is None
        if fs is None:
            from alluxio_tpu.client.file_system import FileSystem

            master = (f"{conf.get(Keys.MASTER_HOSTNAME)}:"
                      f"{conf.get_int(Keys.MASTER_RPC_PORT)}")
            fs = FileSystem(master, conf=conf)
        self._fs = fs
        self._state = _S3State(fs, conf.get(Keys.PROXY_S3_ROOT))
        self._port_conf = conf.get_int(Keys.PROXY_WEB_PORT)
        self._server: Optional[ThreadingHTTPServer] = None
        self.port: Optional[int] = None

    def start(self) -> int:
        state = self._state
        if not state.fs.exists(state.root):
            state.fs.create_directory(state.root, recursive=True,
                                     allow_exists=True)

        class Handler(_S3Handler):
            s3 = state

        from alluxio_tpu.conf import Keys

        bind = self._conf.get(Keys.PROXY_BIND_HOST)
        self._server = ThreadingHTTPServer((bind, self._port_conf),
                                           Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever,
                             name="s3-proxy", daemon=True)
        t.start()
        LOG.info("S3 proxy serving on port %d (root %s)", self.port,
                 state.root)
        return self.port

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        if self._owns_fs:
            self._fs.close()


class _S3Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    s3: _S3State = None  # bound by ProxyProcess.start

    def log_message(self, fmt, *args):
        LOG.debug("s3: " + fmt, *args)

    # -- plumbing ------------------------------------------------------------
    def _send(self, code: int, body: bytes = b"",
              headers: Optional[Dict[str, str]] = None,
              ctype: str = "application/xml") -> None:
        drop = getattr(self, "_unread", 0) > 0
        if drop:
            # responding before the request body was consumed (error
            # path): the unread bytes would desync the next request on
            # a keep-alive connection — close it instead of buffering,
            # and ADVERTISE the close so the client doesn't reuse a
            # dead socket
            self.close_connection = True
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        if drop:
            self.send_header("Connection", "close")
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        if body and self.command != "HEAD":
            self.wfile.write(body)

    def _fail(self, code: int, s3code: str, msg: str) -> None:
        self._send(code, _error(s3code, msg, self.path))

    def _parse(self):
        # request-body accounting for the keep-alive guard in _send
        self._unread = int(self.headers.get("Content-Length") or 0)
        parts = urlsplit(self.path)
        segs = [unquote(s) for s in parts.path.split("/") if s]
        q = {k: v[0] for k, v in parse_qs(parts.query,
                                          keep_blank_values=True).items()}
        bucket = segs[0] if segs else ""
        key = "/".join(segs[1:]) if len(segs) > 1 else ""
        return bucket, key, q

    def _bpath(self, bucket: str) -> str:
        return f"{self.s3.root}/{bucket}"

    def _kpath(self, bucket: str, key: str) -> str:
        return f"{self.s3.root}/{bucket}/{key}"

    def _body(self) -> bytes:
        n = int(self.headers.get("Content-Length") or 0)
        data = self.rfile.read(n) if n else b""
        self._unread = 0
        return data

    def _stream_request_body(self, write, md5=None) -> int:
        """Chunk-copy the request body into ``write`` without buffering
        it whole (parts/objects can be GBs)."""
        total = 0
        while self._unread > 0:
            chunk = self.rfile.read(min(self._CHUNK, self._unread))
            if not chunk:
                break
            self._unread -= len(chunk)
            if md5 is not None:
                md5.update(chunk)
            write(chunk)
            total += len(chunk)
        return total

    # -- verbs ---------------------------------------------------------------
    def do_GET(self):  # noqa: N802
        if self.path.startswith("/api/v1/"):
            return self._rest("GET")
        bucket, key, q = self._parse()
        try:
            if not bucket:
                return self._list_buckets()
            if not key:
                return self._list_objects(bucket, q)
            return self._get_object(bucket, key)
        except FileDoesNotExistError as e:
            self._fail(404, "NoSuchKey", str(e))
        except Exception as e:  # noqa: BLE001
            LOG.warning("s3 GET failed", exc_info=True)
            self._fail(500, "InternalError", str(e))

    def do_HEAD(self):  # noqa: N802
        if self.path.startswith("/api/v1/"):
            # /api/v1/ is reserved for the REST dialect on EVERY verb —
            # a half-hijacked namespace (GET rest, PUT s3) would let an
            # S3 client write objects it can never read back
            return self._rest("HEAD")
        bucket, key, _ = self._parse()
        try:
            info = self.s3.fs.get_status(self._kpath(bucket, key))
            # HEAD: advertise the object's real length; no body is
            # ever written for HEAD so this is protocol-legal
            self.send_response(200)
            self.send_header("Content-Type",
                             "application/octet-stream")
            self.send_header("Content-Length", str(info.length))
            self.send_header("Last-Modified",
                             _iso(info.last_modification_time_ms))
            self.send_header("ETag", f'"{info.file_id:x}"')
            self.end_headers()
        except FileDoesNotExistError:
            self._send(404, b"")
        except Exception:  # noqa: BLE001
            self._send(500, b"")

    def do_PUT(self):  # noqa: N802
        if self.path.startswith("/api/v1/"):
            return self._rest("PUT")
        bucket, key, q = self._parse()
        try:
            if not key:
                self.s3.fs.create_directory(self._bpath(bucket),
                                            recursive=True,
                                            allow_exists=True)
                return self._send(200, b"", {"Location": f"/{bucket}"})
            if "partNumber" in q and "uploadId" in q:
                return self._upload_part(q["uploadId"],
                                         int(q["partNumber"]))
            if not self.s3.fs.exists(self._bpath(bucket)):
                # create_file would recursively materialize the missing
                # bucket as a plain directory — a typo'd bucket must 404
                return self._fail(404, "NoSuchBucket", bucket)
            src = self.headers.get("x-amz-copy-source")
            if src:
                return self._copy_object(bucket, key, unquote(src))
            md5 = hashlib.md5()
            out = self.s3.fs.create_file(self._kpath(bucket, key),
                                         overwrite=True)
            with out:
                self._stream_request_body(out.write, md5)
            self._send(200, b"", {"ETag": f'"{md5.hexdigest()}"'})
        except FileDoesNotExistError as e:
            self._fail(404, "NoSuchBucket", str(e))
        except Exception as e:  # noqa: BLE001
            LOG.warning("s3 PUT failed", exc_info=True)
            self._fail(500, "InternalError", str(e))

    def do_DELETE(self):  # noqa: N802
        if self.path.startswith("/api/v1/"):
            return self._rest("DELETE")
        bucket, key, q = self._parse()
        try:
            if key and "uploadId" in q:
                return self._abort_multipart(q["uploadId"])
            if not key:
                self.s3.fs.delete(self._bpath(bucket))
                return self._send(204)
            self.s3.fs.delete(self._kpath(bucket, key))
            self._send(204)
        except FileDoesNotExistError as e:
            self._fail(404, "NoSuchKey", str(e))
        except DirectoryNotEmptyError as e:
            self._fail(409, "BucketNotEmpty", str(e))
        except Exception as e:  # noqa: BLE001
            self._fail(500, "InternalError", str(e))

    def do_POST(self):  # noqa: N802
        if self.path.startswith("/api/v1/"):
            return self._rest("POST")
        bucket, key, q = self._parse()
        try:
            if "uploads" in q:
                return self._initiate_multipart(bucket, key)
            if "uploadId" in q:
                return self._complete_multipart(bucket, key,
                                                q["uploadId"])
            self._fail(400, "InvalidRequest", "unsupported POST")
        except Exception as e:  # noqa: BLE001
            LOG.warning("s3 POST failed", exc_info=True)
            self._fail(500, "InternalError", str(e))

    # -- native REST paths/streams API ---------------------------------------
    # (reference: ``proxy/{PathsRestServiceHandler,
    # StreamsRestServiceHandler}.java`` — the non-S3 half of the proxy.
    # Streams here are stateless download/upload verbs rather than the
    # reference's stream-id sessions: same coverage, no session table.)
    def _rest(self, verb: str) -> None:
        import json as _json

        # body accounting (the S3 verbs set this in _parse)
        self._unread = int(self.headers.get("Content-Length") or 0)
        parts = urlsplit(self.path)
        q = {k: v[0] for k, v in parse_qs(parts.query,
                                          keep_blank_values=True).items()}
        rest = parts.path[len("/api/v1/"):]
        kind, _, tail = rest.partition("/")
        if kind != "paths" or "/" not in tail:
            return self._rest_err(404, f"no route {parts.path}")
        raw_path, _, op = tail.rpartition("/")
        path = "/" + unquote(raw_path).strip("/")
        fs = self.s3.fs

        def send_json(obj, code=200):
            self._send(code, _json.dumps(obj, default=str).encode(),
                       ctype="application/json")

        streaming = False

        def fail(code: int, msg: str) -> None:
            if streaming:
                # headers already flushed: a second response would be
                # counted as body bytes — abort the connection instead
                self.close_connection = True
            else:
                self._rest_err(code, msg)

        try:
            if verb == "GET" and op == "get-status":
                return send_json(self._rest_info(fs.get_status(path)))
            if verb == "GET" and op == "list-status":
                return send_json([self._rest_info(i)
                                  for i in fs.list_status(path)])
            if verb == "GET" and op == "download":
                info = fs.get_status(path)
                f = fs.open_file(path, info=info)
                try:
                    # from here a failure happens mid-response: the
                    # except handlers must abort, not answer twice
                    streaming = True
                    return self._stream_body(f, 0, info.length, 200, {})
                finally:
                    f.close()
            if verb == "POST" and op == "exists":
                return send_json(fs.exists(path))
            if verb == "POST" and op == "create-directory":
                fs.create_directory(
                    path, recursive=q.get("recursive") == "true",
                    allow_exists=q.get("allowExists") == "true")
                return send_json({})
            if verb == "POST" and op == "delete":
                fs.delete(path, recursive=q.get("recursive") == "true")
                return send_json({})
            if verb == "POST" and op == "rename":
                dst = q.get("dst")
                if not dst:
                    return self._rest_err(
                        400, "rename requires ?dst=<path>")
                fs.rename(path, dst)
                return send_json({})
            if verb == "POST" and op == "upload":
                out = fs.create_file(path, overwrite=True)
                with out:
                    n = self._stream_request_body(out.write)
                return send_json({"bytes": n})
            return self._rest_err(
                404 if verb in ("GET", "POST") else 405,
                f"no op {op!r} for {verb}")
        except FileDoesNotExistError as e:
            fail(404, str(e))
        except DirectoryNotEmptyError as e:
            fail(409, str(e))
        except (InvalidArgumentError, InvalidPathError) as e:
            # client mistakes must be 4xx: retry middleware treats 5xx
            # as server faults and retries the unretryable
            fail(400, str(e))
        except Exception as e:  # noqa: BLE001
            LOG.warning("rest %s %s failed", verb, op, exc_info=True)
            fail(500, f"{type(e).__name__}: {e}")

    @staticmethod
    def _rest_info(i) -> dict:
        return {"path": i.path, "name": i.name, "folder": i.folder,
                "length": i.length,
                "lastModificationTimeMs": i.last_modification_time_ms}

    def _rest_err(self, code: int, msg: str) -> None:
        import json as _json

        self._send(code, _json.dumps({"error": msg}).encode(),
                   ctype="application/json")

    # -- bucket ops ----------------------------------------------------------
    def _list_buckets(self) -> None:
        entries = [i for i in self.s3.fs.list_status(self.s3.root)
                   if i.folder and i.name != _MULTIPART_DIR]
        items = "".join(
            f"<Bucket><Name>{escape(i.name)}</Name>"
            f"<CreationDate>{_iso(i.creation_time_ms)}</CreationDate>"
            f"</Bucket>" for i in sorted(entries, key=lambda x: x.name))
        self._send(200, _xml(
            "<ListAllMyBucketsResult>"
            f"<Buckets>{items}</Buckets></ListAllMyBucketsResult>"))

    def _list_objects(self, bucket: str, q: Dict[str, str]) -> None:
        prefix = q.get("prefix", "")
        delimiter = q.get("delimiter", "")
        max_keys = int(q.get("max-keys", "1000"))
        start_after = q.get("start-after",
                            q.get("continuation-token", ""))
        base = self._bpath(bucket)
        if not self.s3.fs.exists(base):
            return self._fail(404, "NoSuchBucket", bucket)
        # push the prefix's directory component down into the listing so
        # a prefixed request doesn't enumerate the whole bucket
        list_root, infos = base, None
        if "/" in prefix:
            dir_part = prefix.rsplit("/", 1)[0]
            candidate = f"{base}/{dir_part}"
            if self.s3.fs.exists(candidate):
                list_root = candidate
            else:  # prefix directory absent: nothing can match
                infos = []
        if infos is None:
            infos = self.s3.fs.list_status(list_root, recursive=True)
        keys = []
        for i in infos:
            if i.folder:
                continue
            k = i.path[len(base) + 1:]
            if k.startswith(f"{_MULTIPART_DIR}/"):
                continue
            if prefix and not k.startswith(prefix):
                continue
            keys.append((k, i))
        keys.sort(key=lambda t: t[0])
        contents, common = [], []
        seen_prefixes = set()
        more_after = False
        last_emitted = ""
        for k, i in keys:
            if start_after and k <= start_after:
                continue
            if delimiter:
                rest = k[len(prefix):]
                d = rest.find(delimiter)
                if d >= 0:
                    p = prefix + rest[:d + len(delimiter)]
                    # a prefix <= the token was fully emitted on an
                    # earlier page (the token IS that prefix string)
                    if start_after and p <= start_after:
                        continue
                    if p in seen_prefixes:
                        continue
                    # prefixes count against MaxKeys like real S3
                    if len(contents) + len(common) >= max_keys:
                        more_after = True
                        break
                    seen_prefixes.add(p)
                    common.append(p)
                    last_emitted = p
                    continue
            if len(contents) + len(common) >= max_keys:
                more_after = True  # something actually remains
                break
            contents.append((k, i))
            last_emitted = k
        truncated = "true" if more_after else "false"
        body = (f"<ListBucketResult><Name>{escape(bucket)}</Name>"
                f"<Prefix>{escape(prefix)}</Prefix>"
                f"<KeyCount>{len(contents) + len(common)}</KeyCount>"
                f"<MaxKeys>{max_keys}</MaxKeys>"
                f"<IsTruncated>{truncated}</IsTruncated>")
        if more_after and last_emitted:
            body += (f"<NextContinuationToken>"
                     f"{escape(last_emitted)}"
                     f"</NextContinuationToken>")
        for k, i in contents:
            body += (f"<Contents><Key>{escape(k)}</Key>"
                     f"<Size>{i.length}</Size>"
                     f"<LastModified>{_iso(i.last_modification_time_ms)}"
                     f"</LastModified>"
                     f"<ETag>\"{i.file_id:x}\"</ETag></Contents>")
        for p in common:
            body += (f"<CommonPrefixes><Prefix>{escape(p)}</Prefix>"
                     f"</CommonPrefixes>")
        body += "</ListBucketResult>"
        self._send(200, _xml(body))

    # -- object ops ----------------------------------------------------------
    def _get_object(self, bucket: str, key: str) -> None:
        path = self._kpath(bucket, key)
        info = self.s3.fs.get_status(path)
        rng = self.headers.get("Range")
        with self.s3.fs.open_file(path, info=info) as f:
            if rng and rng.startswith("bytes="):
                spec = rng[len("bytes="):]
                a, _, b = spec.partition("-")
                if a:
                    start = int(a)
                    end = int(b) + 1 if b else info.length
                else:  # suffix range: last N bytes
                    start = max(0, info.length - int(b))
                    end = info.length
                end = min(end, info.length)
                if start >= info.length:
                    return self._send(
                        416, _error("InvalidRange",
                                    f"start {start} >= length "
                                    f"{info.length}", self.path),
                        {"Content-Range": f"bytes */{info.length}"})
                return self._stream_body(
                    f, start, end - start, 206, {
                        "Content-Range":
                            f"bytes {start}-{end - 1}/{info.length}",
                        "ETag": f'"{info.file_id:x}"'})
            self._stream_body(f, 0, info.length, 200, {
                "Last-Modified": _iso(info.last_modification_time_ms),
                "ETag": f'"{info.file_id:x}"'})

    _CHUNK = 4 << 20

    def _stream_body(self, f, start: int, n: int, code: int,
                     headers: Dict[str, str]) -> None:
        """Chunked pread -> socket: a multi-GB object must not be
        buffered whole in the proxy's memory."""
        self.send_response(code)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(max(0, n)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        pos, remaining = start, max(0, n)
        while remaining > 0:
            chunk = f.pread(pos, min(self._CHUNK, remaining))
            if not chunk:
                break
            self.wfile.write(chunk)
            pos += len(chunk)
            remaining -= len(chunk)

    def _copy_stream(self, fin, write) -> "hashlib._Hash":
        """Chunked pread -> write with an md5 running alongside (objects
        and parts can be GBs; never buffer them whole)."""
        md5 = hashlib.md5()
        pos = 0
        while True:
            chunk = fin.pread(pos, self._CHUNK)
            if not chunk:
                break
            md5.update(chunk)
            write(chunk)
            pos += len(chunk)
        return md5

    def _copy_object(self, bucket: str, key: str, src: str) -> None:
        segs = [s for s in src.split("/") if s]
        src_path = self._kpath(segs[0], "/".join(segs[1:]))
        with self.s3.fs.open_file(src_path) as fin:
            out = self.s3.fs.create_file(self._kpath(bucket, key),
                                         overwrite=True)
            with out:
                md5 = self._copy_stream(fin, out.write)
        etag = md5.hexdigest()
        self._send(200, _xml(
            f"<CopyObjectResult><ETag>\"{etag}\"</ETag>"
            f"<LastModified>{_iso(int(time.time() * 1000))}"
            f"</LastModified></CopyObjectResult>"))

    # -- multipart -----------------------------------------------------------
    def _initiate_multipart(self, bucket: str, key: str) -> None:
        if not self.s3.fs.exists(self._bpath(bucket)):
            return self._fail(404, "NoSuchBucket", bucket)
        upload_id = uuid.uuid4().hex
        with self.s3.lock:
            self.s3.uploads[upload_id] = (bucket, key)
        self.s3.fs.create_directory(
            f"{self.s3.root}/{_MULTIPART_DIR}/{upload_id}",
            recursive=True, allow_exists=True)
        self._send(200, _xml(
            f"<InitiateMultipartUploadResult>"
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<UploadId>{upload_id}</UploadId>"
            f"</InitiateMultipartUploadResult>"))

    def _upload_part(self, upload_id: str, part: int) -> None:
        with self.s3.lock:
            if upload_id not in self.s3.uploads:
                return self._fail(404, "NoSuchUpload", upload_id)
        md5 = hashlib.md5()
        out = self.s3.fs.create_file(
            f"{self.s3.root}/{_MULTIPART_DIR}/{upload_id}/{part:05d}",
            overwrite=True)
        with out:
            self._stream_request_body(out.write, md5)
        self._send(200, b"", {"ETag": f'"{md5.hexdigest()}"'})

    def _complete_multipart(self, bucket: str, key: str,
                            upload_id: str) -> None:
        with self.s3.lock:
            if upload_id not in self.s3.uploads:
                return self._fail(404, "NoSuchUpload", upload_id)
        if not self.s3.fs.exists(self._bpath(bucket)):
            # bucket deleted mid-upload: must not be re-materialized
            return self._fail(404, "NoSuchBucket", bucket)
        d = f"{self.s3.root}/{_MULTIPART_DIR}/{upload_id}"
        # the client's manifest (CompleteMultipartUpload XML) is the
        # source of truth: assemble exactly the declared parts, in the
        # declared order — never whatever happens to be in the dir
        manifest = self._parse_part_manifest(self._body())
        if manifest is None:  # no/empty body: all uploaded parts in order
            manifest = sorted(int(i.name) for i in
                              self.s3.fs.list_status(d) if not i.folder)
        etags = []
        out = self.s3.fs.create_file(self._kpath(bucket, key),
                                     overwrite=True)
        with out:
            for part in manifest:
                p = f"{d}/{part:05d}"
                if not self.s3.fs.exists(p):
                    out.cancel()
                    return self._fail(400, "InvalidPart",
                                      f"part {part} was not uploaded")
                with self.s3.fs.open_file(p) as fin:
                    etags.append(self._copy_stream(fin, out.write).digest())
        self.s3.fs.delete(d, recursive=True)
        with self.s3.lock:
            self.s3.uploads.pop(upload_id, None)
        agg = hashlib.md5(b"".join(etags)).hexdigest()
        self._send(200, _xml(
            f"<CompleteMultipartUploadResult>"
            f"<Bucket>{escape(bucket)}</Bucket><Key>{escape(key)}</Key>"
            f"<ETag>\"{agg}-{len(etags)}\"</ETag>"
            f"</CompleteMultipartUploadResult>"))

    @staticmethod
    def _parse_part_manifest(body: bytes):
        """Part numbers from the CompleteMultipartUpload request body,
        in document order; None when absent/unparseable."""
        if not body:
            return None
        try:
            import xml.etree.ElementTree as ET

            root = ET.fromstring(body)
            parts = [int(e.text) for e in root.iter()
                     if e.tag.endswith("PartNumber")]
            return parts or None
        except Exception:  # noqa: BLE001 malformed body: fall back
            return None

    def _abort_multipart(self, upload_id: str) -> None:
        with self.s3.lock:
            self.s3.uploads.pop(upload_id, None)
        d = f"{self.s3.root}/{_MULTIPART_DIR}/{upload_id}"
        try:
            self.s3.fs.delete(d, recursive=True)
        except FileDoesNotExistError:
            pass
        self._send(204)
