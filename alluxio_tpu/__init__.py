"""alluxio_tpu: a TPU-native data orchestration framework.

A brand-new framework with the capabilities of the reference distributed
data-orchestration layer (Alluxio 2.5): a journaled metadata master that
federates mounted under-storages, a fleet of tiered cache workers, a
filesystem client, and a job service for background data movement — designed
TPU-first:

- the client page cache's top tier is **TPU HBM** (pages materialize as
  ``jax.Array`` with refcounted pin leases integrated with JAX liveness);
- the local data path is **short-circuit mmap over /dev/shm** handed to XLA
  with no extra host copy, instead of a FUSE -> page-cache -> copy hop;
- intra-slice distribution uses **ICI collectives** (``shard_map`` ring
  all-gather of cached shards) instead of socket streams; DCN gRPC covers
  cross-slice and the control plane.

Layer map mirrors SURVEY.md section 1 (reference layers L0-L8).
"""

__version__ = "0.1.0"

# Lazy convenience re-exports; submodules are imported on demand so that the
# pure-control-plane pieces never drag in jax.
_LAZY = {
    "FileSystem": "alluxio_tpu.client.file_system",
    "AlluxioURI": "alluxio_tpu.utils.uri",
    "Configuration": "alluxio_tpu.conf.configuration",
    "PropertyKey": "alluxio_tpu.conf.property_key",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name])
        return getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
