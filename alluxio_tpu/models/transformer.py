"""Flagship consumer model: a compact ViT-style transformer, TPU-first.

This is the model the benchmarks and graft entry drive end-to-end: the
data plane's output (decoded image batches from cached blocks) feeds it.
Pure-JAX parameter pytree with explicit sharding rules so the same
forward runs single-chip or pjit-sharded over a mesh (dp over batch, tp
over heads/MLP, sp via ring attention for long sequences).

Design notes (per the TPU guide): all matmuls are bf16 einsums shaped to
tile the MXU (model dims multiples of 128 at real sizes); no Python-level
control flow inside jit; layers scanned where depth is large.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from alluxio_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from alluxio_tpu.parallel.ring_attention import (
    reference_attention, ring_attention_local,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_or_patch_dim: int = 768   # input projection dim (patch bytes)
    d_model: int = 256
    n_heads: int = 8
    d_ff: int = 1024
    n_layers: int = 4
    n_classes: int = 1000
    max_len: int = 256
    dtype: Any = jnp.bfloat16
    #: >0 switches the FFN to a top-1 MoE with this many experts
    #: (expert-parallel over the model axis; the second model family)
    moe_experts: int = 0

    @property
    def d_head(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: TransformerConfig, key) -> Dict[str, Any]:
    keys = jax.random.split(key, 4 + cfg.n_layers)
    scale = 0.02

    def dense(k, shape):
        return (jax.random.normal(k, shape) * scale).astype(cfg.dtype)

    params: Dict[str, Any] = {
        "embed": dense(keys[0], (cfg.vocab_or_patch_dim, cfg.d_model)),
        "pos": dense(keys[1], (cfg.max_len, cfg.d_model)),
        "head": dense(keys[2], (cfg.d_model, cfg.n_classes)),
        "final_ln": {"scale": jnp.ones(cfg.d_model, cfg.dtype)},
        "layers": [],
    }
    for i in range(cfg.n_layers):
        k = jax.random.split(keys[4 + i], 6)
        layer = {
            "ln1": {"scale": jnp.ones(cfg.d_model, cfg.dtype)},
            "wqkv": dense(k[0], (cfg.d_model, 3, cfg.n_heads, cfg.d_head)),
            "wo": dense(k[1], (cfg.n_heads, cfg.d_head, cfg.d_model)),
            "ln2": {"scale": jnp.ones(cfg.d_model, cfg.dtype)},
        }
        if cfg.moe_experts > 0:
            from alluxio_tpu.parallel.moe import init_moe_params

            layer["moe"] = init_moe_params(
                k[2], n_experts=cfg.moe_experts, d_model=cfg.d_model,
                d_ff=cfg.d_ff, dtype=cfg.dtype)
        else:
            layer["w1"] = dense(k[2], (cfg.d_model, cfg.d_ff))
            layer["w2"] = dense(k[3], (cfg.d_ff, cfg.d_model))
        params["layers"].append(layer)
    return params


def param_shardings(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs: tensor-parallel over heads/FF (``model`` axis),
    replicated elsewhere — the megatron-style split XLA turns into
    all-reduces on ICI."""
    layer = {
        "ln1": {"scale": P()},
        "wqkv": P(None, None, MODEL_AXIS, None),
        "wo": P(MODEL_AXIS, None, None),
        "ln2": {"scale": P()},
    }
    if cfg.moe_experts > 0:
        from alluxio_tpu.parallel.moe import moe_param_specs

        layer["moe"] = moe_param_specs()
    else:
        layer["w1"] = P(None, MODEL_AXIS)
        layer["w2"] = P(MODEL_AXIS, None)
    return {
        "embed": P(),
        "pos": P(),
        "head": P(),
        "final_ln": {"scale": P()},
        "layers": [layer for _ in range(cfg.n_layers)],
    }


def _rms_norm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def _attention(x, layer, cfg: TransformerConfig, *,
               seq_axis: Optional[str] = None):
    qkv = jnp.einsum("btd,dshk->sbthk", x, layer["wqkv"])
    q, k, v = qkv[0], qkv[1], qkv[2]
    if seq_axis is not None:
        out = ring_attention_local(q, k, v, axis_name=seq_axis, causal=False)
    else:
        out = reference_attention(q, k, v, causal=False)
    return jnp.einsum("bthk,hkd->btd", out, layer["wo"])


def _mlp(x, layer):
    if "moe" in layer:
        from alluxio_tpu.parallel.moe import moe_ffn

        return moe_ffn(layer["moe"], x)
    h = jnp.einsum("btd,df->btf", x, layer["w1"])
    h = jax.nn.gelu(h)
    return jnp.einsum("btf,fd->btd", h, layer["w2"])


def forward_with_aux(params, tokens, cfg: TransformerConfig, *,
                     seq_axis: Optional[str] = None):
    """tokens: (B, T, vocab_or_patch_dim) float inputs (e.g. flattened
    patches from the decode op). Returns ((B, n_classes) logits, aux)
    where ``aux`` is the summed MoE load-balance loss (0 when dense) —
    without it top-1 routing collapses every token onto one expert."""
    x = jnp.einsum("btp,pd->btd", tokens.astype(cfg.dtype), params["embed"])
    t = x.shape[1]
    x = x + params["pos"][:t][None]
    aux = jnp.float32(0.0)
    for layer in params["layers"]:
        x = x + _attention(_rms_norm(x, layer["ln1"]["scale"]), layer, cfg,
                           seq_axis=seq_axis)
        ffn_in = _rms_norm(x, layer["ln2"]["scale"])
        if "moe" in layer:
            from alluxio_tpu.parallel.moe import load_balance_loss

            aux = aux + load_balance_loss(
                layer["moe"], ffn_in).astype(jnp.float32)
        x = x + _mlp(ffn_in, layer)
    x = _rms_norm(x, params["final_ln"]["scale"])
    pooled = jnp.mean(x, axis=1)
    logits = jnp.einsum("bd,dc->bc", pooled,
                        params["head"]).astype(jnp.float32)
    return logits, aux


def forward(params, tokens, cfg: TransformerConfig, *,
            seq_axis: Optional[str] = None):
    return forward_with_aux(params, tokens, cfg, seq_axis=seq_axis)[0]


#: weight of the Switch-style balance loss in the training objective
MOE_AUX_WEIGHT = 0.01


def loss_fn(params, tokens, labels, cfg: TransformerConfig, *,
            seq_axis: Optional[str] = None):
    logits, aux = forward_with_aux(params, tokens, cfg,
                                   seq_axis=seq_axis)
    logp = jax.nn.log_softmax(logits)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1).mean()
    return nll + MOE_AUX_WEIGHT * aux


def images_to_tokens(images, patch: int = 16):
    """(B,H,W,C) -> (B, T, patch*patch*C): patchify outside the model so
    the embed einsum is one big MXU matmul."""
    b, h, w, c = images.shape
    ph, pw = h // patch, w // patch
    x = images.reshape(b, ph, patch, pw, patch, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, ph * pw, patch * patch * c)
