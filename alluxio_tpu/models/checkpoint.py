"""Model checkpoints stored IN the namespace.

The model-plane half of SURVEY §5.4 (the control plane already has
journal/checkpoint/backup): sharded train state serializes through the
``FileSystem`` client into cached, UFS-persistable files, and restores
straight back onto a device mesh — so checkpoints ride the same tiered
cache, replication, and persistence machinery as training data, and a
restore on a warm cluster reads from HBM/MEM tiers instead of cold
object storage.

Layout under ``<path>/``: ``tree.msgpack`` (structure + dtypes/shapes +
a manifest) and one ``leaf-<i>.bin`` per array (raw C-order bytes).
Arrays sharded over a mesh are fetched whole (``np.asarray``) on save —
single-host scope; multi-host writers shard the leaf files by process.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def _flatten(tree):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return leaves, treedef


def save_pytree(fs, path: str, tree, *, write_type=None) -> int:
    """Serialize a pytree of arrays under ``path``; returns leaf count."""
    import msgpack

    kwargs = {"write_type": write_type} if write_type else {}
    leaves, treedef = _flatten(tree)
    metas = []
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        metas.append({"dtype": str(arr.dtype), "shape": list(arr.shape)})
        fs.write_all(f"{path}/leaf-{i}.bin",
                     np.ascontiguousarray(arr).tobytes(), **kwargs)
    blob = msgpack.packb({
        "treedef": str(treedef),
        "n_leaves": len(leaves),
        "metas": metas,
    }, use_bin_type=True)
    fs.write_all(f"{path}/tree.msgpack", blob, **kwargs)
    return len(leaves)


def load_pytree(fs, path: str, *, like=None, shardings=None):
    """Restore a pytree saved by :func:`save_pytree`.

    - ``like``: a pytree with the SAME structure (e.g. freshly-inited
      params) supplying the treedef — required because treedefs don't
      round-trip through strings.
    - ``shardings``: optional matching pytree of shardings; leaves are
      ``jax.device_put`` onto them (restore-to-mesh).
    """
    import msgpack

    import jax

    if like is None:
        raise ValueError("load_pytree needs `like=` (a structure-matched "
                         "pytree, e.g. freshly initialized params)")
    meta = msgpack.unpackb(fs.read_all(f"{path}/tree.msgpack"),
                           raw=False)
    like_leaves, treedef = _flatten(like)
    if meta["n_leaves"] != len(like_leaves):
        raise ValueError(
            f"checkpoint has {meta['n_leaves']} leaves; `like` has "
            f"{len(like_leaves)} — structure mismatch")
    out_leaves = []
    shard_leaves = None
    if shardings is not None:
        # shardings are unregistered pytree nodes (leaves by default);
        # the is_leaf only needs to keep explicit Nones as leaves
        shard_leaves = jax.tree_util.tree_flatten(
            shardings, is_leaf=lambda x: x is None)[0]
        if len(shard_leaves) != len(like_leaves):
            raise ValueError(
                f"shardings tree has {len(shard_leaves)} leaves; model "
                f"has {len(like_leaves)} — pass a structure-matched "
                f"tree (use None for replicated leaves)")
    for i, (m, ref) in enumerate(zip(meta["metas"], like_leaves)):
        raw = fs.read_all(f"{path}/leaf-{i}.bin")
        arr = np.frombuffer(raw, dtype=np.dtype(m["dtype"])) \
            .reshape(m["shape"])
        if list(np.shape(ref)) != m["shape"]:
            raise ValueError(
                f"leaf {i}: checkpoint shape {m['shape']} != model "
                f"shape {list(np.shape(ref))}")
        ref_dtype = np.asarray(ref).dtype
        if np.dtype(m["dtype"]) != ref_dtype:
            raise ValueError(
                f"leaf {i}: checkpoint dtype {m['dtype']} != model "
                f"dtype {ref_dtype} — a silent dtype change would "
                f"recompile and shift numerics")
        if shard_leaves is not None and shard_leaves[i] is not None:
            out_leaves.append(jax.device_put(arr, shard_leaves[i]))
        else:
            out_leaves.append(jax.numpy.asarray(arr))
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def save_train_state(fs, path: str, params, opt_state, *, step: int,
                     write_type=None) -> None:
    """Checkpoint (params, opt_state, step) under ``path``."""
    kwargs = {"write_type": write_type} if write_type else {}
    save_pytree(fs, f"{path}/params", params, write_type=write_type)
    save_pytree(fs, f"{path}/opt", opt_state, write_type=write_type)
    fs.write_all(f"{path}/STEP", str(step).encode(), **kwargs)


def load_train_state(fs, path: str, *, like_params, like_opt,
                     param_shardings=None, opt_shardings=None):
    """Restore (params, opt_state, step) saved by save_train_state."""
    params = load_pytree(fs, f"{path}/params", like=like_params,
                         shardings=param_shardings)
    opt = load_pytree(fs, f"{path}/opt", like=like_opt,
                      shardings=opt_shardings)
    step = int(fs.read_all(f"{path}/STEP").decode())
    return params, opt, step


def latest_step(fs, base: str) -> Optional[int]:
    """Highest ``step-<n>`` child under ``base`` (checkpoint dirs written
    as ``{base}/step-{n}``), or None."""
    from alluxio_tpu.utils.exceptions import FileDoesNotExistError

    try:
        infos = fs.list_status(base)
    except FileDoesNotExistError:
        return None  # no checkpoints yet; transient RPC errors RAISE —
        # "cannot list" must not read as "resume from scratch"
    steps = []
    for i in infos:
        name = i.name
        if name.startswith("step-"):
            try:
                steps.append(int(name[len("step-"):]))
            except ValueError:
                continue
    return max(steps) if steps else None
