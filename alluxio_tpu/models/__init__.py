"""Flagship consumer models driven by the data plane."""

from alluxio_tpu.models.transformer import (  # noqa: F401
    TransformerConfig, forward, images_to_tokens, init_params, loss_fn,
    param_shardings,
)
