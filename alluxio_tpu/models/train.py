"""Sharded training step for the flagship model.

Builds the pjit-compiled train step the graft entry and benchmarks use:
dp over the ``data`` mesh axis, tp over ``model`` (param shardings from
``transformer.param_shardings``), optional sequence parallelism (ring
attention over ``data``) for the long-context variant. XLA inserts the
psum/all-reduce collectives from the shardings — no hand-written
communication on the compute path.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from alluxio_tpu.models.transformer import (
    TransformerConfig, forward, init_params, loss_fn, param_shardings,
)
from alluxio_tpu.parallel.mesh import DATA_AXIS


def make_sharded_train_state(cfg: TransformerConfig, mesh, *,
                             learning_rate: float = 1e-3, seed: int = 0):
    """(params, opt_state, tx) with params placed per the sharding rules."""
    tx = optax.adamw(learning_rate)
    specs = param_shardings(cfg)
    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec), specs,
        is_leaf=lambda x: isinstance(x, P))

    init = jax.jit(functools.partial(init_params, cfg),
                   out_shardings=shardings)
    params = init(jax.random.PRNGKey(seed))
    opt_state = jax.jit(tx.init)(params)
    return params, opt_state, tx, shardings


def make_train_step(cfg: TransformerConfig, mesh, tx, shardings, *,
                    seq_parallel: bool = False):
    """Compile the full step: grads (dp all-reduce), adamw update (sharded
    like params), loss out."""
    batch_sharding = NamedSharding(mesh, P(DATA_AXIS))
    seq_axis = DATA_AXIS if seq_parallel else None

    if seq_parallel:
        # tokens sharded along T (context parallel) instead of batch
        batch_sharding = NamedSharding(mesh, P(None, DATA_AXIS))

    def step(params, opt_state, tokens, labels):
        loss, grads = jax.value_and_grad(loss_fn)(
            params, tokens, labels, cfg, seq_axis=seq_axis)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    label_sharding = NamedSharding(mesh, P(DATA_AXIS)) if not seq_parallel \
        else NamedSharding(mesh, P())
    return jax.jit(
        step,
        in_shardings=(shardings, None, batch_sharding, label_sharding),
        out_shardings=(shardings, None, None),
        donate_argnums=(0, 1))


def make_eval_step(cfg: TransformerConfig, mesh, shardings):
    batch_sharding = NamedSharding(mesh, P(DATA_AXIS))

    def step(params, tokens):
        return forward(params, tokens, cfg)

    return jax.jit(step, in_shardings=(shardings, batch_sharding))
