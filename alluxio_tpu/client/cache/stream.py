"""Page-cached file stream.

Re-design of ``core/client/fs/src/main/java/alluxio/client/file/cache/
LocalCacheFileInStream.java:38``: random reads (FUSE-style 4k) are served
page-at-a-time from the local page cache, falling through to the inner
FileInStream on miss — the reference's Presto/FUSE fast path, and bench
config #2's subject.
"""

from __future__ import annotations

from typing import Optional

from alluxio_tpu.client.cache.manager import LocalCacheManager
from alluxio_tpu.client.cache.meta import PageId


class CachingFileInStream:
    def __init__(self, inner, cache: LocalCacheManager) -> None:
        self._inner = inner
        self._cache = cache
        self._page_size = cache.page_size
        self.info = inner.info
        self._file_key = f"{inner.info.file_id:x}"
        self._pos = 0

    @property
    def length(self) -> int:
        return self._inner.length

    def seek(self, pos: int) -> None:
        self._pos = pos
        self._inner.seek(pos)

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self.length - self._pos
        data = self.pread(self._pos, n)
        self._pos += len(data)
        return data

    def pread(self, offset: int, n: int) -> bytes:
        out = bytearray()
        pos = offset
        end = min(offset + n, self.length)
        while pos < end:
            page_index = pos // self._page_size
            off_in_page = pos % self._page_size
            want = min(end - pos, self._page_size - off_in_page)
            chunk = self._read_page(page_index, off_in_page, want)
            if not chunk:
                break
            out.extend(chunk)
            pos += len(chunk)
        return bytes(out)

    def _read_page(self, page_index: int, offset: int, n: int) -> bytes:
        pid = PageId(self._file_key, page_index)
        hit = self._cache.get(pid, offset, n)
        if hit is not None:
            return hit
        page_start = page_index * self._page_size
        page_len = min(self._page_size, self.length - page_start)
        if page_len <= 0:
            return b""
        page = self._inner.pread(page_start, page_len)
        self._cache.put(pid, page)
        return page[offset:offset + n]

    def block_stream(self, index: int):
        """Delegate to the inner stream — the zero-copy JAX loader bypasses
        the page cache for whole-block reads (the HBM store covers those)."""
        return self._inner.block_stream(index)

    def close(self) -> None:
        self._inner.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
