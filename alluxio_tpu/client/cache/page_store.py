"""Page stores: where page bytes live.

Re-design of ``core/client/fs/src/main/java/alluxio/client/file/cache/store/
{LocalPageStore,RocksPageStore}.java``:
- **LocalPageStore** — one file per page under ``<dir>/<file_id>/<index>``
  (the reference's layout), mmap-able for zero-copy gets.
- **MemPageStore** — dict-backed (tests + HOST tier on tmpfs-less boxes).

The HBM device store lives in ``hbm_store.py``.
"""

from __future__ import annotations

import os
import shutil
import threading
from typing import Dict, Optional

from alluxio_tpu.client.cache.meta import PageId


class PageStore:
    def put(self, page_id: PageId, data: bytes) -> None:
        raise NotImplementedError

    def get(self, page_id: PageId, offset: int = 0,
            length: int = -1) -> Optional[bytes]:
        raise NotImplementedError

    def delete(self, page_id: PageId) -> bool:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemPageStore(PageStore):
    def __init__(self) -> None:
        self._pages: Dict[PageId, bytes] = {}
        self._lock = threading.Lock()

    def put(self, page_id: PageId, data: bytes) -> None:
        with self._lock:
            self._pages[page_id] = bytes(data)

    def get(self, page_id: PageId, offset: int = 0,
            length: int = -1) -> Optional[bytes]:
        with self._lock:
            data = self._pages.get(page_id)
        if data is None:
            return None
        end = len(data) if length < 0 else offset + length
        return data[offset:end]

    def delete(self, page_id: PageId) -> bool:
        with self._lock:
            return self._pages.pop(page_id, None) is not None


class LocalPageStore(PageStore):
    """One file per page (reference: ``LocalPageStore.java``)."""

    def __init__(self, root: str) -> None:
        self._root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, page_id: PageId) -> str:
        safe = page_id.file_id.replace("/", "_")
        return os.path.join(self._root, safe, str(page_id.page_index))

    def put(self, page_id: PageId, data: bytes) -> None:
        p = self._path(page_id)
        os.makedirs(os.path.dirname(p), exist_ok=True)
        tmp = p + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, p)

    def get(self, page_id: PageId, offset: int = 0,
            length: int = -1) -> Optional[bytes]:
        p = self._path(page_id)
        try:
            fd = os.open(p, os.O_RDONLY)
        except FileNotFoundError:
            return None
        try:
            if length < 0:
                length = os.fstat(fd).st_size - offset
            return os.pread(fd, length, offset)
        finally:
            os.close(fd)

    def delete(self, page_id: PageId) -> bool:
        p = self._path(page_id)
        try:
            os.remove(p)
        except FileNotFoundError:
            return False
        d = os.path.dirname(p)
        try:
            if not os.listdir(d):
                os.rmdir(d)
        except OSError:
            pass
        return True

    def restore_pages(self):
        """Enumerate pages already on disk (async restore on startup —
        reference: LocalCacheManager restore)."""
        for file_dir in os.listdir(self._root):
            fdir = os.path.join(self._root, file_dir)
            if not os.path.isdir(fdir):
                continue
            for idx in os.listdir(fdir):
                try:
                    size = os.path.getsize(os.path.join(fdir, idx))
                    yield PageId(file_dir, int(idx)), size
                except (ValueError, OSError):
                    continue

    def close(self) -> None:
        pass

    def purge(self) -> None:
        shutil.rmtree(self._root, ignore_errors=True)
        os.makedirs(self._root, exist_ok=True)
