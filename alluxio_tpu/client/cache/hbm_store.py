"""HBM page store: cached pages resident in TPU device memory.

**TPU-native addition with no reference analogue** (the reference's top
tier is host RAM behind FUSE; SURVEY.md north star: "the tiered block store
gains an HBM tier materialized as jax.Array"). Pages are ``jax.Array``s of
uint8 living on a device; a warm get is a device-resident array — zero
host traffic, consumable by a jitted step directly.

Eviction vs JAX liveness (SURVEY.md hard part "HBM-tier eviction vs JAX
liveness"): a page handed to a consumer may be woven into an XLA
computation; deleting the backing buffer under it would be a
use-after-free. So gets return **pin leases**: the store refuses to evict a
page while leases are outstanding (refcount), mirroring the worker's
``ClientRWLock`` pin discipline. Dropping the lease (or the consumer using
``jax.Array`` copies) releases it. XLA itself keeps buffers alive while an
in-flight computation references them, so the lease only needs to cover
the window between ``get`` and dispatch.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, TYPE_CHECKING

from alluxio_tpu.client.cache.meta import PageId

if TYPE_CHECKING:  # pragma: no cover
    import jax


class DevicePageLease:
    """A pinned device page; ``array`` is the jax.Array. Close to unpin."""

    def __init__(self, store: "HbmPageStore", page_id: PageId, array) -> None:
        self._store = store
        self.page_id = page_id
        self.array = array
        self._closed = False

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._store._unpin(self.page_id)

    def __enter__(self) -> "DevicePageLease":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


class HbmPageStore:
    """Device-memory page store with pin-lease eviction safety.

    Eviction policy is a pluggable :class:`CacheEvictor` (LRU default) —
    the same SPI the host page cache uses (reference:
    ``client/file/cache/evictor/CacheEvictor.java``) — with pinned pages
    skipped: the evictor nominates victims, the store vetoes pinned ones.
    """

    def __init__(self, capacity_bytes: int, device=None,
                 evictor: str = "LRU") -> None:
        import jax  # deferred: control-plane processes never import jax

        from alluxio_tpu.client.cache.evictor import CacheEvictor

        self._jax = jax
        self._capacity = capacity_bytes
        self._device = device or jax.devices()[0]
        self._pages: Dict[PageId, "jax.Array"] = {}
        self._sizes: Dict[PageId, int] = {}
        self._pins: Dict[PageId, int] = {}
        self._used = 0
        self._lock = threading.RLock()
        self._evictor = evictor if not isinstance(evictor, str) \
            else CacheEvictor.create(evictor)

    # -- capacity -----------------------------------------------------------
    @property
    def used_bytes(self) -> int:
        with self._lock:
            return self._used

    @property
    def capacity_bytes(self) -> int:
        return self._capacity

    @property
    def page_count(self) -> int:
        with self._lock:
            return len(self._pages)

    def has(self, page_id: PageId) -> bool:
        with self._lock:
            return page_id in self._pages

    # -- put/get ------------------------------------------------------------
    def put(self, page_id: PageId, host_buffer) -> bool:
        """Transfer a host buffer (bytes / numpy view / mmap view) into
        device memory. Returns False if it cannot fit after eviction."""
        import numpy as np

        arr = np.frombuffer(host_buffer, dtype=np.uint8)
        with self._lock:
            if page_id in self._pages:
                return True
            if arr.nbytes > self._capacity:
                return False  # precheck: skip a doomed transfer
            # device_put from a zero-copy numpy view: one DMA host->HBM;
            # retention bookkeeping lives in adopt() (single code path)
            return self.adopt(page_id,
                              self._jax.device_put(arr, self._device))

    def adopt(self, page_id: PageId, device_array) -> bool:
        """Retain an ALREADY device-resident array (e.g. the loader just
        ``device_put`` it for a consumer) without a second transfer.
        Returns False when it cannot fit after eviction."""
        with self._lock:
            if page_id in self._pages:
                return True
            size = device_array.nbytes
            if size > self._capacity or not self._ensure_room(size):
                return False
            self._pages[page_id] = device_array
            self._sizes[page_id] = size
            self._used += size
            self._evictor.update_on_put(page_id)
            return True

    def get(self, page_id: PageId) -> Optional[DevicePageLease]:
        """Warm hit: the device array itself, pinned until lease close."""
        with self._lock:
            arr = self._pages.get(page_id)
            if arr is None:
                return None
            self._pins[page_id] = self._pins.get(page_id, 0) + 1
            self._evictor.update_on_get(page_id)
            return DevicePageLease(self, page_id, arr)

    def _unpin(self, page_id: PageId) -> None:
        with self._lock:
            n = self._pins.get(page_id, 0) - 1
            if n <= 0:
                self._pins.pop(page_id, None)
            else:
                self._pins[page_id] = n

    def delete(self, page_id: PageId, force: bool = False) -> bool:
        """Evict = drop the store's reference ONLY. Never ``arr.delete()``:
        that invalidates the buffer for every holder, including a consumer
        that got this array from an earlier ``get`` — JAX frees device
        memory once the last Python reference dies, which is exactly the
        liveness contract we want."""
        with self._lock:
            if not force and self._pins.get(page_id, 0) > 0:
                return False  # pinned by a live lease
            arr = self._pages.pop(page_id, None)
            if arr is None:
                return False
            self._used -= self._sizes.pop(page_id, 0)
            self._pins.pop(page_id, None)
            self._evictor.update_on_delete(page_id)
            del arr
            return True

    def _ensure_room(self, size: int) -> bool:
        """Evict per the evictor's policy until ``size`` fits, skipping
        pinned pages (the evictor nominates the first evictable candidate
        in policy order; pinned pages are excluded by predicate)."""
        while self._used + size > self._capacity:
            victim = self._evictor.evict_matching(
                lambda p: self._pins.get(p, 0) == 0 and p in self._pages)
            if victim is None:
                # evictor view stale/empty: any unpinned page as last resort
                victim = next((pid for pid in self._pages
                               if self._pins.get(pid, 0) == 0), None)
            if victim is None:
                return False
            self.delete(victim)
        return True

    def pinned_count(self) -> int:
        with self._lock:
            return sum(1 for n in self._pins.values() if n > 0)

    def close(self) -> None:
        with self._lock:
            for pid in list(self._pages):
                self.delete(pid, force=True)
