"""Page cache metadata types.

Re-design of ``core/client/fs/src/main/java/alluxio/client/file/cache/
{PageId,PageInfo,MetaStore}.java``: pages are fixed-size (default 1MB)
slices of a file, keyed by (file_id, page_index).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Iterator, Optional, Set, Tuple


@dataclass(frozen=True)
class PageId:
    file_id: str
    page_index: int

    def __str__(self) -> str:
        return f"{self.file_id}#{self.page_index}"


@dataclass
class PageInfo:
    page_id: PageId
    page_size: int
    tier: str = "HOST"  # HBM | HOST | DISK


class PageMetaStore:
    """Tracks cached pages + per-tier usage
    (reference: ``cache/DefaultMetaStore``)."""

    def __init__(self) -> None:
        self._pages: Dict[PageId, PageInfo] = {}
        self._bytes_by_tier: Dict[str, int] = {}
        self._lock = threading.RLock()

    def add(self, info: PageInfo) -> None:
        with self._lock:
            old = self._pages.get(info.page_id)
            if old is not None:
                self._bytes_by_tier[old.tier] = (
                    self._bytes_by_tier.get(old.tier, 0) - old.page_size)
            self._pages[info.page_id] = info
            self._bytes_by_tier[info.tier] = (
                self._bytes_by_tier.get(info.tier, 0) + info.page_size)

    def remove(self, page_id: PageId) -> Optional[PageInfo]:
        with self._lock:
            info = self._pages.pop(page_id, None)
            if info is not None:
                self._bytes_by_tier[info.tier] = (
                    self._bytes_by_tier.get(info.tier, 0) - info.page_size)
            return info

    def get(self, page_id: PageId) -> Optional[PageInfo]:
        with self._lock:
            return self._pages.get(page_id)

    def has(self, page_id: PageId) -> bool:
        with self._lock:
            return page_id in self._pages

    def bytes_in_tier(self, tier: str) -> int:
        with self._lock:
            return self._bytes_by_tier.get(tier, 0)

    def pages_of_file(self, file_id: str) -> Iterator[PageId]:
        with self._lock:
            return iter([pid for pid in self._pages
                         if pid.file_id == file_id])

    def __len__(self) -> int:
        with self._lock:
            return len(self._pages)
