"""LocalCacheManager: the client-embedded page cache.

Re-design of ``core/client/fs/src/main/java/alluxio/client/file/cache/
{CacheManager.java:82,LocalCacheManager.java:75}`` with the TPU twist: an
optional **HBM tier above the host tier**. Layout:

    HBM (jax.Array pages, pin-leased)   <- get_device() hits
    HOST/DISK (LocalPageStore | MemPageStore, LRU/LFU evicted)

``put`` lands pages in the host store; ``get_device`` promotes a host page
into HBM on access (clock-like warm-up) and serves device-resident arrays
on repeat access — the second epoch of a training run never touches host
memory for warm pages.
"""

from __future__ import annotations

import threading
from typing import Optional

from alluxio_tpu.client.cache.evictor import CacheEvictor
from alluxio_tpu.client.cache.hbm_store import DevicePageLease, HbmPageStore
from alluxio_tpu.client.cache.meta import PageId, PageInfo, PageMetaStore
from alluxio_tpu.client.cache.page_store import (
    LocalPageStore, MemPageStore, PageStore,
)
from alluxio_tpu.metrics import metrics


class LocalCacheManager:
    def __init__(self, store: PageStore, *, capacity_bytes: int,
                 page_size: int = 1 << 20,
                 evictor: Optional[CacheEvictor] = None,
                 hbm_store: Optional[HbmPageStore] = None) -> None:
        self._store = store
        self._capacity = capacity_bytes
        self.page_size = page_size
        self._evictor = evictor or CacheEvictor.create("LRU")
        self._meta = PageMetaStore()
        self._hbm = hbm_store
        self._lock = threading.RLock()
        self._m = metrics()

    @staticmethod
    def from_conf(conf) -> "LocalCacheManager":
        from alluxio_tpu.conf import Keys

        store = LocalPageStore(conf.get(Keys.USER_CLIENT_CACHE_DIR))
        hbm_bytes = conf.get_bytes(Keys.USER_CLIENT_CACHE_HBM_SIZE)
        hbm = HbmPageStore(hbm_bytes) if hbm_bytes > 0 else None
        return LocalCacheManager(
            store, capacity_bytes=conf.get_bytes(Keys.USER_CLIENT_CACHE_SIZE),
            page_size=conf.get_bytes(Keys.USER_CLIENT_CACHE_PAGE_SIZE),
            evictor=CacheEvictor.create(conf.get(Keys.USER_CLIENT_CACHE_EVICTOR)),
            hbm_store=hbm)

    # -- host-tier put/get ---------------------------------------------------
    def put(self, page_id: PageId, data: bytes) -> bool:
        with self._lock:
            if self._meta.has(page_id):
                return True
            while self._meta.bytes_in_tier("HOST") + len(data) > self._capacity:
                victim = self._evictor.evict()
                if victim is None:
                    return False
                self._delete_host(victim)
            self._store.put(page_id, data)
            self._meta.add(PageInfo(page_id, len(data), tier="HOST"))
            self._evictor.update_on_put(page_id)
            self._m.counter("Client.PagesCached").inc()
            return True

    def get(self, page_id: PageId, offset: int = 0,
            length: int = -1) -> Optional[bytes]:
        with self._lock:
            if not self._meta.has(page_id):
                self._m.counter("Client.PageCacheMisses").inc()
                return None
        data = self._store.get(page_id, offset, length)
        if data is None:  # store lost it (restart, purge)
            with self._lock:
                self._meta.remove(page_id)
                self._evictor.update_on_delete(page_id)
            self._m.counter("Client.PageCacheMisses").inc()
            return None
        self._evictor.update_on_get(page_id)
        self._m.counter("Client.PageCacheHits").inc()
        return data

    def has(self, page_id: PageId) -> bool:
        return self._meta.has(page_id)

    def _delete_host(self, page_id: PageId) -> None:
        self._store.delete(page_id)
        self._meta.remove(page_id)
        self._evictor.update_on_delete(page_id)
        self._m.counter("Client.PagesEvicted").inc()

    def delete(self, page_id: PageId) -> bool:
        with self._lock:
            existed = self._meta.has(page_id)
            if existed:
                self._delete_host(page_id)
        if self._hbm is not None:
            self._hbm.delete(page_id)
        return existed

    def delete_file(self, file_id: str) -> int:
        n = 0
        for pid in list(self._meta.pages_of_file(file_id)):
            if self.delete(pid):
                n += 1
        return n

    # -- HBM tier ------------------------------------------------------------
    @property
    def hbm(self) -> Optional[HbmPageStore]:
        return self._hbm

    def get_device(self, page_id: PageId,
                   host_fallback=None) -> Optional[DevicePageLease]:
        """Device-resident get: HBM hit returns the jax.Array lease; on
        miss, promote from the host tier (or ``host_fallback()`` bytes)
        into HBM, then serve. None if the page is nowhere."""
        if self._hbm is None:
            return None
        lease = self._hbm.get(page_id)
        if lease is not None:
            self._m.counter("Client.HbmPageHits").inc()
            return lease
        data = self.get(page_id)
        if data is None and host_fallback is not None:
            data = host_fallback()
            if data is not None:
                self.put(page_id, data)
        if data is None:
            return None
        self._m.counter("Client.HbmPagePromotions").inc()
        if self._hbm.put(page_id, data):
            return self._hbm.get(page_id)
        return None

    # -- maintenance ---------------------------------------------------------
    def restore(self) -> int:
        """Re-adopt pages an earlier process left in a LocalPageStore."""
        n = 0
        if isinstance(self._store, LocalPageStore):
            for pid, size in self._store.restore_pages():
                self._meta.add(PageInfo(pid, size, tier="HOST"))
                self._evictor.update_on_put(pid)
                n += 1
        return n

    def stats(self) -> dict:
        return {
            "pages": len(self._meta),
            "host_bytes": self._meta.bytes_in_tier("HOST"),
            "hbm_bytes": self._hbm.used_bytes if self._hbm else 0,
            "hbm_pinned": self._hbm.pinned_count() if self._hbm else 0,
        }

    def close(self) -> None:
        self._store.close()
        if self._hbm is not None:
            self._hbm.close()
