"""Page-cache evictors.

Re-design of ``core/client/fs/src/main/java/alluxio/client/file/cache/
evictor/{CacheEvictor,LRUCacheEvictor,LFUCacheEvictor}.java``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional

from alluxio_tpu.client.cache.meta import PageId


class CacheEvictor:
    def update_on_get(self, page_id: PageId) -> None:
        raise NotImplementedError

    def update_on_put(self, page_id: PageId) -> None:
        raise NotImplementedError

    def update_on_delete(self, page_id: PageId) -> None:
        raise NotImplementedError

    def evict(self) -> Optional[PageId]:
        """The next victim (not removed; caller calls update_on_delete)."""
        raise NotImplementedError

    def evict_matching(self, pred) -> Optional[PageId]:
        """First victim IN POLICY ORDER satisfying ``pred`` (reference:
        the evictor's evictMatching shape) — lets a caller skip pages it
        cannot evict (e.g. pinned) without abandoning the policy."""
        raise NotImplementedError

    @staticmethod
    def create(kind: str) -> "CacheEvictor":
        k = kind.upper()
        if k == "LRU":
            return LRUCacheEvictor()
        if k == "LFU":
            return LFUCacheEvictor()
        raise ValueError(f"unknown evictor {kind}")


class LRUCacheEvictor(CacheEvictor):
    def __init__(self) -> None:
        self._order: "OrderedDict[PageId, None]" = OrderedDict()
        self._lock = threading.Lock()

    def update_on_get(self, page_id: PageId) -> None:
        with self._lock:
            if page_id in self._order:
                self._order.move_to_end(page_id)

    def update_on_put(self, page_id: PageId) -> None:
        with self._lock:
            self._order[page_id] = None
            self._order.move_to_end(page_id)

    def update_on_delete(self, page_id: PageId) -> None:
        with self._lock:
            self._order.pop(page_id, None)

    def evict(self) -> Optional[PageId]:
        with self._lock:
            return next(iter(self._order)) if self._order else None

    def evict_matching(self, pred) -> Optional[PageId]:
        with self._lock:
            return next((p for p in self._order if pred(p)), None)


class LFUCacheEvictor(CacheEvictor):
    def __init__(self) -> None:
        self._counts: Dict[PageId, int] = {}
        self._lock = threading.Lock()

    def update_on_get(self, page_id: PageId) -> None:
        with self._lock:
            if page_id in self._counts:
                self._counts[page_id] += 1

    def update_on_put(self, page_id: PageId) -> None:
        with self._lock:
            self._counts[page_id] = self._counts.get(page_id, 0) + 1

    def update_on_delete(self, page_id: PageId) -> None:
        with self._lock:
            self._counts.pop(page_id, None)

    def evict(self) -> Optional[PageId]:
        with self._lock:
            if not self._counts:
                return None
            return min(self._counts, key=self._counts.get)

    def evict_matching(self, pred) -> Optional[PageId]:
        with self._lock:
            cands = [p for p in self._counts if pred(p)]
            return min(cands, key=self._counts.get) if cands else None
