"""Client-embedded page cache with an HBM top tier
(reference: ``core/client/fs/.../cache``; HBM tier is TPU-native)."""

from alluxio_tpu.client.cache.manager import LocalCacheManager  # noqa: F401
from alluxio_tpu.client.cache.meta import PageId, PageInfo  # noqa: F401
from alluxio_tpu.client.cache.page_store import (  # noqa: F401
    LocalPageStore, MemPageStore,
)
