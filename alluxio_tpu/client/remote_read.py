"""Parallel remote-read data plane: striped multi-stream DCN reads,
replica fan-out, hedged requests, and zero-join chunk assembly.

Client-side counterpart of the worker's striped cold-read pipeline
(``worker/ufs_fetch.py``): once the HBM/DRAM tiers and the cold path are
fast, the remote *warm* read is the last single-connection hot path —
``GrpcBlockInStream.pread`` used to open one stream to one policy-chosen
replica, pull chunks strictly sequentially, and re-join them through a
``bytearray``.  One DCN connection's bandwidth capped cross-host
throughput, and one slow worker set the tail.

This module rebuilds that path as a pipelined, parallel subsystem:

- **striped multi-stream reads** — a read larger than one stripe is
  split into ranges fetched over concurrent ``read_block`` streams,
  fanned out across replicas when the master reports more than one
  location, and across pooled gRPC channels (distinct TCP connections)
  to a single worker otherwise (the Hoard / network-image-loading
  result: many modest streams beat one connection);
- **zero-join assembly** — stripes land via ``memoryview`` writes into
  ONE preallocated buffer; no per-chunk ``bytearray.extend`` and no
  final whole-read ``bytes()`` re-copy.  ``jax.device_put``-bound
  callers get the buffer as a view (``numpy.frombuffer`` wraps it
  zero-copy);
- **pipelined windowing** — a bounded in-flight window keeps stripes
  streaming while the consumer drains, capping readahead past the
  contiguous frontier (and with it peak wasted work when a read dies);
- **hedged requests** — a stripe that exceeds a latency quantile of its
  worker's rolling EWMA is re-issued to another source; first answer
  wins, the loser's stream is cancelled.  Straggler robustness for
  free.

Observability: ``Client.RemoteRead{Stripes,Hedges,HedgeWins,Reroutes,
Bytes}`` counters + the ``Client.RemoteReadTtfb`` timer, and an
``atpu.client.remote_read`` span per striped read that joins the
caller's trace so the input doctor can attribute remote-read stalls.
"""

from __future__ import annotations

import functools as _functools
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from alluxio_tpu.metrics import metrics
from alluxio_tpu.utils import tracing as _tracing
from alluxio_tpu.utils.exceptions import (
    BlockDoesNotExistError, UnavailableError,
)
from alluxio_tpu.utils.striping import plan_stripes

#: hedge delays below this never fire — on a same-host CI cluster the
#: EWMA can sit at microseconds, and hedging every stripe there is a
#: hedge storm, not tail protection
MIN_HEDGE_DELAY_S = 0.002

#: pooled channels (= distinct TCP connections) to ONE worker never
#: exceed this, whatever the stripe concurrency — the per-worker
#: connection budget against a single peer
MAX_POOLED_CHANNELS = 8


@dataclass(frozen=True)
class RemoteReadConf:
    """Tuning for the striped remote-read pipeline (see
    ``atpu.user.remote.read.*`` in ``conf/property_key.py``)."""

    #: bytes per stripe; reads ≤ this ride the legacy single stream.
    #: 0 disables striping entirely (byte-identical legacy path).
    stripe_size: int = 4 << 20
    #: stripes of one read in flight concurrently
    concurrency: int = 4
    #: readahead cap: stripes are only issued while their offset is
    #: within this many bytes of the consumer's drain point
    window_bytes: int = 32 << 20
    #: latency quantile of a worker's rolling EWMA above which a stripe
    #: is hedged to another source; 0 disables hedging
    hedge_quantile: float = 0.95
    #: per-tenant cap on concurrent stripe streams (incl. hedges)
    #: across every striped read in this process; 0 = unlimited.
    #: The frontier stripe of each read bypasses the cap (liveness).
    tenant_stripe_limit: int = 0
    #: the tenant these reads bill against (the client's principal)
    tenant: str = ""
    #: commit large stripe chunks/scratch through the native plan
    #: executor (``atpu.user.native.fastpath.enabled``): GIL-free
    #: memcpy into the assembly buffer; plain memoryview copy is the
    #: byte-identical fallback
    native_fastpath: bool = True

    @classmethod
    def from_conf(cls, conf) -> "RemoteReadConf":
        from alluxio_tpu.conf import Keys
        from alluxio_tpu.security.user import get_client_user

        return cls(
            stripe_size=max(0, conf.get_bytes(
                Keys.USER_REMOTE_READ_STRIPE_SIZE)),
            concurrency=max(1, conf.get_int(
                Keys.USER_REMOTE_READ_CONCURRENCY)),
            window_bytes=max(0, conf.get_bytes(
                Keys.USER_REMOTE_READ_WINDOW_BYTES)),
            hedge_quantile=min(1.0, max(0.0, conf.get_float(
                Keys.USER_REMOTE_READ_HEDGE_QUANTILE))),
            tenant_stripe_limit=max(0, conf.get_int(
                Keys.USER_QOS_STRIPE_LIMIT)),
            tenant=get_client_user(conf),
            native_fastpath=conf.get_bool(
                Keys.USER_NATIVE_FASTPATH_ENABLED),
        )

    @property
    def enabled(self) -> bool:
        return self.stripe_size > 0


def choose_route(length: int, *, same_host_shm: bool = False,
                 batch=None, batch_ops: int = 1,
                 striped: Optional[RemoteReadConf] = None) -> str:
    """The read-plane routing decision, in one place (docs/small_reads.md
    has the full matrix):

    - ``"shm"``     — same-host + SHM transport live: mmap the segment,
                      zero RPC/serialize/copy per read
    - ``"batch"``   — a multi-op batch of small reads: coalesce into
                      ``read_many`` RPCs (one wire round trip per batch)
    - ``"striped"`` — a read larger than one stripe: the parallel
                      multi-stream plane below
    - ``"stream"``  — everything else: the legacy single ``read_block``
                      stream (and the byte-identical disabled path)

    Precedence is top-down: same-host beats everything (no wire at
    all), batching beats striping only because it is checked for small
    ops striping would never take. Every fast route falls back one row
    down on failure — the router can only make reads faster, never fail
    them. ``batch`` is a ``BatchReadConf`` (duck-typed to avoid a
    module cycle with ``block_streams``)."""
    if same_host_shm:
        return "shm"
    if batch is not None and batch.enabled and batch_ops > 1 and \
            length <= batch.max_op_bytes:
        return "batch"
    if striped is not None and striped.enabled and \
            length > striped.stripe_size:
        return "striped"
    return "stream"


@_functools.lru_cache(maxsize=64)
def _z_score(quantile: float) -> float:
    """Normal z-score of a quantile — cached: the hedger evaluates it
    for every in-flight stripe on every coordinator wake-up, always
    with the same configured quantile."""
    from statistics import NormalDist

    return NormalDist().inv_cdf(quantile)




class LatencyStats:
    """Rolling per-worker stripe-latency EWMA + EWMA absolute deviation
    (the TCP-RTO estimator shape).  The hedge threshold for quantile
    ``q`` is ``ewma + z(q) * dev`` — a normal-tail read of "this stripe
    is past the worker's q-quantile".  No threshold is produced until a
    worker has a few samples: hedging on zero history is a coin flip."""

    MIN_SAMPLES = 5
    _ALPHA = 0.2  # EWMA weight of the newest sample

    def __init__(self) -> None:
        self._lock = threading.Lock()
        #: key -> (ewma_s, ewma_abs_dev_s, samples)
        self._stats: Dict[str, Tuple[float, float, int]] = {}

    def observe(self, key: str, latency_s: float) -> None:
        with self._lock:
            prev = self._stats.get(key)
            if prev is None:
                self._stats[key] = (latency_s, latency_s / 2.0, 1)
                return
            ewma, dev, n = prev
            err = abs(latency_s - ewma)
            a = self._ALPHA
            self._stats[key] = (ewma + a * (latency_s - ewma),
                                dev + a * (err - dev), n + 1)

    @staticmethod
    def _z(quantile: float) -> float:
        return _z_score(min(0.999, max(0.5, quantile)))

    def hedge_delay_s(self, key: str, quantile: float) -> Optional[float]:
        """Seconds an in-flight stripe on ``key`` may run before it is
        past the worker's ``quantile`` and worth hedging; None while the
        worker has too little history to call anything a straggler."""
        if quantile <= 0.0:
            return None
        with self._lock:
            st = self._stats.get(key)
        if st is None or st[2] < self.MIN_SAMPLES:
            return None
        ewma, dev, _ = st
        return max(MIN_HEDGE_DELAY_S, ewma + self._z(quantile) * dev)

    def snapshot(self) -> Dict[str, Tuple[float, float, int]]:
        with self._lock:
            return dict(self._stats)


class ReadSource:
    """One independent path to block bytes — a replica, or one pooled
    channel (TCP connection) of a replica.

    ``open(offset, length, chunk_size)`` returns a *stream handle*: an
    iterable of ``{"data": bytes, "source": tier}`` messages covering
    exactly ``[offset, offset+length)`` of the block, with a
    ``cancel()`` method that aborts the underlying transfer (hedging
    cancels the loser).  ``worker_key`` groups sources that die together
    (all channels of one worker); ``key`` identifies the latency-EWMA
    bucket."""

    key: str = ""
    worker_key: str = ""
    address = None  # WorkerNetAddress for mark_failed plumbing

    def open(self, offset: int, length: int, chunk_size: int):
        raise NotImplementedError


class GrpcReadSource(ReadSource):
    """A replica worker reached over one pooled gRPC channel."""

    def __init__(self, worker, address, channel: int, *, block_id: int,
                 ufs: Optional[dict] = None, cache: bool = True) -> None:
        self._worker = worker
        self._block_id = block_id
        self._ufs = ufs
        self._cache = cache
        self.channel = channel
        self.address = address
        self.worker_key = address.key() if address is not None \
            else f"worker#{id(worker)}"
        self.key = self.worker_key if channel == 0 \
            else f"{self.worker_key}~{channel}"

    def open(self, offset: int, length: int, chunk_size: int):
        return self._worker.read_block_stream(
            self._block_id, offset=offset, length=length,
            chunk_size=chunk_size, ufs=self._ufs, cache=self._cache,
            channel=self.channel)


class _Attempt:
    """One in-flight stripe transfer (a primary, a re-route, or a
    hedge).  Direct attempts write chunks straight into the shared
    buffer under the stripe's write lock; hedges buffer into scratch
    and commit wholesale if they win."""

    __slots__ = ("stripe", "source", "direct", "is_hedge", "started",
                 "handle", "cancelled", "scratch")

    def __init__(self, stripe: int, source: ReadSource, *,
                 direct: bool, is_hedge: bool) -> None:
        self.stripe = stripe
        self.source = source
        self.direct = direct
        self.is_hedge = is_hedge
        self.started = time.perf_counter()
        self.handle = None
        self.cancelled = False
        self.scratch: Optional[bytearray] = None if direct else bytearray()


class StripedRead:
    """One parallel read of ``[offset, offset+length)`` of a block.

    The caller's thread is the coordinator: it waits on the scheduler
    condition, fires overdue hedges, and drains the contiguous frontier
    (``read_view`` drains instantly; ``iter_views`` at the consumer's
    pace, which is what the in-flight window meters against)."""

    def __init__(self, runtime: "RemoteReadRuntime", *, block_id: int,
                 sources: List[ReadSource], offset: int, length: int,
                 chunk_size: int = 1 << 20,
                 on_failed: Optional[Callable] = None) -> None:
        if not sources:
            raise UnavailableError(
                f"no sources for striped read of block {block_id}")
        self._rt = runtime
        self._conf = runtime.conf
        self.block_id = block_id
        self._sources = sources
        self._offset = offset
        self._n = max(0, length)
        self._chunk = max(1, chunk_size)
        self._on_failed = on_failed
        self._stripes = plan_stripes(self._n, self._conf.stripe_size)
        k = len(self._stripes)
        self._buf = bytearray(self._n)
        self._cond = threading.Condition()
        self._stripe_locks = [threading.Lock() for _ in range(k)]
        self._winner: List[Optional[_Attempt]] = [None] * k
        self._landed = [False] * k
        #: contiguous bytes received from stripe start by direct
        #: attempts (monotone): lets the consumer drain INTO the
        #: frontier stripe at chunk granularity, so streaming TTFB is
        #: O(chunk) like the single-stream path, not O(stripe). Safe
        #: across re-routes/hedges because every source serves the same
        #: block bytes — a rewrite repeats identical values.
        self._progress = [0] * k
        self._attempts: List[List[_Attempt]] = [[] for _ in range(k)]
        self._routed: List[set] = [set() for _ in range(k)]
        self._hedged = [False] * k
        self._frontier = 0          # first not-landed stripe index
        self._drained = 0           # bytes the consumer has taken
        self._next_submit = 0
        self._active = 0
        self._dead_workers: set = set()
        self._started = False
        #: a direct submit was denied by the tenant stripe budget; the
        #: coordinator polls instead of waiting indefinitely (budget
        #: frees on OTHER reads' completions, which don't notify us)
        self._budget_deferred = False
        #: bytes (range-relative) actually served when a source's
        #: stream ended cleanly short of its range — a shrunk UFS
        #: object served truncated, mirroring the legacy reader
        self._truncated_at: Optional[int] = None
        self._error: Optional[BaseException] = None
        self._last_failure: Optional[BaseException] = None
        self._first_byte_at: Optional[float] = None
        self._t0 = time.perf_counter()
        self.source_tag: Optional[str] = None  # serving tier of any chunk
        self.hedges = 0
        self.hedge_wins = 0
        self.reroutes = 0
        self._m = metrics()
        self._span = self._open_span()
        #: phase accumulators (only written when this read is traced):
        #: executor queue wait and transfer ("wire") time of winning
        #: attempts, summed across stripes
        self._queue_ms = 0.0
        self._wire_ms = 0.0
        self._latency_recorded = False

    # -- tracing -------------------------------------------------------------
    def _open_span(self):
        t = _tracing.tracer()
        if not t.enabled:
            return None
        ctx = _tracing.current_trace_context()
        span = _tracing.Span(
            "atpu.client.remote_read", _tracing.new_span_id(),
            ctx.span_id if ctx else None,
            ctx.trace_id if ctx else _tracing.new_trace_id(),
            sampled=ctx.sampled if ctx else t._sample())
        span.tags = {"block_id": str(self.block_id),
                     "bytes": str(self._n),
                     "stripes": str(len(self._stripes)),
                     "sources": str(len(self._sources))}
        return span

    def _record_latency(self) -> None:
        """Size-bucketed end-to-end latency with a trace exemplar: the
        ``Client.ReadLatency.{le4k,le64k,le1m,gt1m}`` timers are what
        ``fsadmin report history`` watches for p99 regressions, and the
        exemplar (this read's trace id, when sampled) links an outlier
        bucket straight to an attributable trace."""
        if self._n <= 0 or self._latency_recorded:
            return
        self._latency_recorded = True
        from alluxio_tpu.metrics.stall import size_bucket

        exemplar = self._span.trace_id \
            if self._span is not None and self._span.sampled else None
        self._m.timer(
            f"Client.ReadLatency.{size_bucket(self._n)}").update(
            time.perf_counter() - self._t0, exemplar=exemplar)

    def _close_span(self) -> None:
        self._record_latency()
        if self._span is None:
            return
        if self._queue_ms > 0.0:
            self._span.phase("queue_wait", self._queue_ms)
        if self._wire_ms > 0.0:
            self._span.phase("wire", self._wire_ms)
        self._span.duration_ms = (time.perf_counter() - self._t0) * 1000.0
        self._span.tags["hedges"] = str(self.hedges)
        self._span.tags["hedge_wins"] = str(self.hedge_wins)
        self._span.tags["reroutes"] = str(self.reroutes)
        if self._error is not None:
            self._span.error = \
                f"{type(self._error).__name__}: {self._error}"
        if self._span.sampled:
            _tracing.tracer().record(self._span)
        self._span = None

    # -- scheduling (all under self._cond) -----------------------------------
    def _frontier_bytes(self) -> int:
        if self._frontier >= len(self._stripes):
            return self._n
        return self._stripes[self._frontier][0] + \
            self._progress[self._frontier]

    def _pick_source_locked(self, stripe: int,
                            avoid_key: Optional[str] = None
                            ) -> Optional[ReadSource]:
        """Next healthy, untried source for a stripe — round-robin
        rotated by stripe index so concurrent stripes spread across the
        replica set; a hedge prefers a different worker than the slow
        attempt's (``avoid_key``)."""
        ns = len(self._sources)
        candidates = []
        for j in range(ns):
            s = self._sources[(stripe + j) % ns]
            if s.worker_key in self._dead_workers:
                continue
            if id(s) in self._routed[stripe]:
                continue
            candidates.append(s)
        if not candidates:
            return None
        if avoid_key is not None:
            for s in candidates:
                if s.worker_key != avoid_key:
                    return s
        return candidates[0]

    def _submit_locked(self, stripe: int, source: ReadSource, *,
                       direct: bool, is_hedge: bool,
                       force_budget: bool = False) -> Optional[_Attempt]:
        # tenant stripe budget FIRST, before any booking: a denied
        # submit must leave no trace so the coordinator simply retries
        # once budget frees.  The frontier stripe (and failure
        # re-routes) pass force_budget — the cap shapes readahead and
        # hedging, never liveness.
        if not self._rt.budget.acquire(self._conf.tenant,
                                       self._conf.tenant_stripe_limit,
                                       force=force_budget):
            if is_hedge:
                self._m.counter("Client.QosHedgesSuppressed").inc()
            else:
                self._m.counter("Client.QosStripesDeferred").inc()
                self._budget_deferred = True
            return None
        a = _Attempt(stripe, source, direct=direct, is_hedge=is_hedge)
        self._attempts[stripe].append(a)
        self._routed[stripe].add(id(source))
        self._active += 1
        try:
            self._rt.executor().submit(self._run_attempt, a)
        except BaseException as e:  # noqa: BLE001 - runtime shut down
            # un-book the attempt so the read fails instead of hanging
            # on a task that will never run (close() raced this read)
            self._attempts[stripe].remove(a)
            self._active -= 1
            self._rt.budget.release(self._conf.tenant)
            if self._error is None:
                self._error = UnavailableError(
                    f"remote-read executor unavailable: {e}")
                self._cancel_all_locked()
                self._cond.notify_all()
            return None
        return a

    def _submit_eligible_locked(self) -> None:
        window = self._conf.window_bytes
        k = len(self._stripes)
        self._budget_deferred = False
        while self._next_submit < k:
            i = self._next_submit
            if self._active >= self._conf.concurrency:
                return
            rel_off = self._stripes[i][0]
            # the frontier stripe is always admissible — a window
            # smaller than one stripe must not deadlock the read
            if i != self._frontier and window > 0 and \
                    rel_off >= self._drained + window:
                return
            src = self._pick_source_locked(i)
            if src is None:
                if self._active == 0 and self._error is None:
                    self._error = self._last_failure or UnavailableError(
                        f"no healthy sources left for block "
                        f"{self.block_id}")
                    self._cond.notify_all()
                return
            a = self._submit_locked(i, src, direct=True, is_hedge=False,
                                    force_budget=(i == self._frontier))
            if a is None:
                # budget-deferred (retry once a stream frees) or the
                # read just died on an executor failure
                return
            self._next_submit += 1

    def _fire_hedges_locked(self) -> None:
        q = self._conf.hedge_quantile
        if q <= 0.0 or len(self._sources) < 2:
            return
        now = time.perf_counter()
        for i in range(self._frontier, min(self._next_submit,
                                           len(self._stripes))):
            if self._landed[i] or self._hedged[i]:
                continue
            live = [a for a in self._attempts[i] if not a.cancelled]
            if len(live) != 1:
                continue
            a = live[0]
            if a.handle is None:
                continue  # still queued/opening: nothing to outrace
            delay = self._rt.stats.hedge_delay_s(a.source.key, q)
            if delay is None or now - a.started < delay:
                continue
            src = self._pick_source_locked(i,
                                           avoid_key=a.source.worker_key)
            if src is None:
                # no untried healthy source, and within one read the
                # candidate set only shrinks: stop considering this
                # stripe, or the overdue deadline would spin the
                # coordinator awake at ~1 kHz until the stripe lands
                self._hedged[i] = True
                continue
            # marked hedged either way: a budget-suppressed hedge is
            # given up, not retried — spinning the coordinator on an
            # overdue deadline while the tenant is at cap would burn
            # CPU for a race the budget says we cannot afford
            self._hedged[i] = True
            a2 = self._submit_locked(i, src, direct=False, is_hedge=True)
            if a2 is not None:
                self.hedges += 1
                self._m.counter("Client.RemoteReadHedges").inc()

    def _wait_timeout_locked(self) -> Optional[float]:
        """Coordinator wait bound: the earliest hedge deadline, tightened
        to a short poll while the tenant stripe budget is deferring our
        submissions (another read's completion frees budget without
        notifying this read's condition)."""
        t = self._next_hedge_deadline_locked()
        if self._budget_deferred:
            return 0.05 if t is None else min(t, 0.05)
        return t

    def _next_hedge_deadline_locked(self) -> Optional[float]:
        """Seconds until the earliest in-flight stripe becomes hedge-
        eligible; None when nothing will (wait for completions only)."""
        q = self._conf.hedge_quantile
        if q <= 0.0 or len(self._sources) < 2:
            return None
        now = time.perf_counter()
        best: Optional[float] = None
        for i in range(self._frontier, min(self._next_submit,
                                           len(self._stripes))):
            if self._landed[i] or self._hedged[i]:
                continue
            live = [a for a in self._attempts[i] if not a.cancelled]
            if len(live) != 1 or live[0].handle is None:
                continue
            delay = self._rt.stats.hedge_delay_s(live[0].source.key, q)
            if delay is None:
                continue
            remain = live[0].started + delay - now
            best = remain if best is None else min(best, remain)
        if best is None:
            return None
        return max(best, 0.001)

    def _cancel_all_locked(self) -> None:
        for attempts in self._attempts:
            for a in attempts:
                if not a.cancelled:
                    a.cancelled = True
                    if a.handle is not None:
                        try:
                            a.handle.cancel()
                        except Exception:  # noqa: BLE001 - already dead
                            pass

    # -- attempt side (executor threads) -------------------------------------
    def _native_copy(self, dst_off: int, data) -> bool:
        """Commit ``data`` into the assembly buffer at ``dst_off``
        through the native executor — a GIL-free memcpy, so a multi-MB
        stripe commit no longer stalls every other Python thread.
        False (fastpath off, library missing, small chunk, any native
        problem) means the caller does the plain memoryview copy,
        which is byte-identical."""
        if not self._conf.native_fastpath:
            return False
        from alluxio_tpu.client import fastpath

        if len(data) < fastpath.MIN_COPY_BYTES or not fastpath.available():
            return False
        return fastpath.copy_into(self._buf, dst_off, data, host="stripe")

    def _note_first_byte(self) -> None:
        if self._first_byte_at is not None:
            return
        with self._cond:
            if self._first_byte_at is None:
                self._first_byte_at = time.perf_counter()
                self._m.timer("Client.RemoteReadTtfb").update(
                    self._first_byte_at - self._t0)

    def _run_attempt(self, a: _Attempt) -> None:
        i = a.stripe
        rel_off, ln = self._stripes[i]
        lock = self._stripe_locks[i]
        buf = memoryview(self._buf)
        src_tag = None
        # the transfer clock starts HERE, not at submit: time spent
        # queued behind other attempts in the shared executor is not
        # the worker's latency — counting it would hedge queued stripes
        # into the same saturated queue and corrupt the EWMA
        now = time.perf_counter()
        if self._span is not None:
            with self._cond:
                self._queue_ms += (now - a.started) * 1000.0
        a.started = now
        try:
            handle = a.source.open(self._offset + rel_off, ln, self._chunk)
            with self._cond:
                if a.cancelled or self._error is not None:
                    try:
                        handle.cancel()
                    except Exception:  # noqa: BLE001
                        pass
                    self._attempt_gone_locked(a)
                    return
                a.handle = handle
            pos = 0
            for msg in handle:
                data = msg.get("data") or b""
                src_tag = msg.get("source", src_tag)
                if not data:
                    continue
                self._note_first_byte()
                if pos + len(data) > ln:
                    raise UnavailableError(
                        f"over-long stripe: worker sent {pos + len(data)}B "
                        f"for a {ln}B range of block {self.block_id}")
                if a.direct:
                    with lock:
                        if self._winner[i] is not None or a.cancelled:
                            try:
                                handle.cancel()
                            except Exception:  # noqa: BLE001
                                pass
                            with self._cond:
                                self._attempt_gone_locked(a)
                            return
                        if not self._native_copy(rel_off + pos, data):
                            buf[rel_off + pos:
                                rel_off + pos + len(data)] = data
                    with self._cond:
                        if pos + len(data) > self._progress[i]:
                            self._progress[i] = pos + len(data)
                            if i == self._frontier:
                                self._cond.notify_all()
                else:
                    a.scratch.extend(data)
                pos += len(data)
            if pos != ln:
                # a CLEANLY short stream is data, not sickness: the
                # source is serving a shorter object than the metadata
                # says (shrunk UFS object read-through — the worker
                # serves available bytes by design). Finish truncated
                # like the legacy single-stream reader did; raising
                # here would also blacklist a healthy worker.
                self._stripe_truncated(a, pos)
                return
            self._complete_attempt(a, src_tag)
        except BaseException as e:  # noqa: BLE001 - routed, not raised
            self._attempt_failed(a, e)

    def _attempt_gone_locked(self, a: _Attempt) -> None:
        """Remove a finished/cancelled attempt from the live set and
        wake the coordinator so it can resubmit within the window.
        Every booked attempt holds exactly one tenant-budget unit
        (acquired in ``_submit_locked``); it is returned here."""
        try:
            self._attempts[a.stripe].remove(a)
        except ValueError:
            pass
        self._active -= 1
        self._rt.budget.release(self._conf.tenant)
        self._cond.notify_all()

    def _complete_attempt(self, a: _Attempt, src_tag: Optional[str]) -> None:
        i = a.stripe
        rel_off, ln = self._stripes[i]
        lock = self._stripe_locks[i]
        with lock:
            if self._winner[i] is not None:
                with self._cond:
                    self._attempt_gone_locked(a)
                return
            self._winner[i] = a
            if not a.direct and not self._native_copy(rel_off, a.scratch):
                memoryview(self._buf)[rel_off:rel_off + ln] = a.scratch
        latency = time.perf_counter() - a.started
        self._rt.stats.observe(a.source.key, latency)
        self._m.counter("Client.RemoteReadStripes").inc()
        self._m.counter("Client.RemoteReadBytes").inc(ln)
        with self._cond:
            if self._span is not None:
                # winning transfers only: the read was blocked on these
                self._wire_ms += latency * 1000.0
            self._attempt_gone_locked(a)
            self._landed[i] = True
            if src_tag is not None:
                self.source_tag = src_tag
            if a.is_hedge:
                self.hedge_wins += 1
                self._m.counter("Client.RemoteReadHedgeWins").inc()
            # the loser of a hedged stripe is pure waste now: cancel it
            for other in list(self._attempts[i]):
                if not other.cancelled:
                    other.cancelled = True
                    if other.handle is not None:
                        try:
                            other.handle.cancel()
                        except Exception:  # noqa: BLE001
                            pass
            while self._frontier < len(self._stripes) and \
                    self._landed[self._frontier]:
                self._frontier += 1
            self._submit_eligible_locked()
            self._cond.notify_all()

    def _stripe_truncated(self, a: _Attempt, served: int) -> None:
        """Accept a truncated stripe and finish the read at the
        truncation point: land this and every later stripe (their bytes
        will never arrive) and cancel their in-flight attempts. Earlier
        stripes keep streaming — the data before the point is real."""
        i = a.stripe
        rel_off, ln = self._stripes[i]
        commit = False
        with self._stripe_locks[i]:
            if self._winner[i] is None:
                self._winner[i] = a
                commit = True
                if not a.direct and served > 0 and not self._native_copy(
                        rel_off, memoryview(a.scratch)[:served]):
                    memoryview(self._buf)[rel_off:rel_off + served] = \
                        memoryview(a.scratch)[:served]
        with self._cond:
            self._attempt_gone_locked(a)
            if not commit or self._error is not None:
                return
            point = rel_off + served
            if self._truncated_at is None or point < self._truncated_at:
                self._truncated_at = point
            for j in range(i, len(self._stripes)):
                if not self._landed[j]:
                    self._landed[j] = True
                    for other in self._attempts[j]:
                        if not other.cancelled:
                            other.cancelled = True
                            if other.handle is not None:
                                try:
                                    other.handle.cancel()
                                except Exception:  # noqa: BLE001
                                    pass
            self._next_submit = len(self._stripes)
            while self._frontier < len(self._stripes) and \
                    self._landed[self._frontier]:
                self._frontier += 1
            self._cond.notify_all()

    def _attempt_failed(self, a: _Attempt, exc: BaseException) -> None:
        with self._cond:
            self._attempt_gone_locked(a)
            i = a.stripe
            if a.cancelled or self._landed[i] or self._error is not None:
                return  # benign: we lost a hedge race or the read died
            self._last_failure = exc
            self._dead_workers.add(a.source.worker_key)
            if self._on_failed is not None and \
                    not isinstance(exc, BlockDoesNotExistError):
                # a missing block is a stale location, not a sick
                # worker: route around it here without poisoning the
                # store's failed-worker memory
                try:
                    self._on_failed(a.source.address)
                except Exception:  # noqa: BLE001 - advisory
                    pass
            live = [x for x in self._attempts[i] if not x.cancelled]
            if live:
                return  # the stripe's hedge is still running; it decides
            src = self._pick_source_locked(i)
            if src is None:
                self._error = exc
                self._cancel_all_locked()
                self._cond.notify_all()
                return
            self.reroutes += 1
            self._m.counter("Client.RemoteReadReroutes").inc()
            # sole surviving attempt for the stripe: direct writes are
            # safe again (the failed writer is finished by definition).
            # NOT a hedge even when the failed attempt was one — this
            # transfer races nothing, and counting it as a hedge win
            # would inflate the rate operators tune hedge.quantile by.
            # force_budget: a budget-denied re-route would orphan the
            # stripe forever (it is behind _next_submit and has no
            # live attempt left to finish it) — repair beats the cap
            self._submit_locked(i, src, direct=True, is_hedge=False,
                                force_budget=True)

    # -- consumer side -------------------------------------------------------
    def _start_locked(self) -> None:
        if not self._started:
            self._started = True
            self._submit_eligible_locked()

    def _effective_n(self) -> int:
        return self._n if self._truncated_at is None \
            else min(self._n, self._truncated_at)

    def read_view(self) -> memoryview:
        """Assemble the whole range and return it as a zero-copy view
        over the preallocated buffer (drains the frontier instantly, so
        the window only meters in-flight stripes). A truncated source
        (shrunk object) shortens the view, like the legacy reader."""
        if self._n == 0:
            self._close_span()
            return memoryview(b"")
        try:
            with self._cond:
                self._start_locked()
                while self._frontier < len(self._stripes) and \
                        self._error is None:
                    self._drained = self._frontier_bytes()
                    self._submit_eligible_locked()
                    self._fire_hedges_locked()
                    self._cond.wait(self._wait_timeout_locked())
                if self._error is not None:
                    raise self._error
                self._drained = self._n
                return memoryview(self._buf)[:self._effective_n()]
        finally:
            self._close_span()

    def iter_views(self, chunk_size: int = 1 << 20) -> Iterator[memoryview]:
        """Yield the range in ascending order, each chunk as soon as
        the stripe containing it lands; stripes are only issued while
        within ``window_bytes`` of the consumer's drain point, so a
        slow consumer bounds in-flight memory instead of buffering the
        whole read.

        ``read_view`` (instant drain) is what the block streams use
        today; this is the drain-paced surface for sequential
        streamers (FUSE/proxy-style consumers, the remote-read bench's
        TTFB probe) and is where the window conf actually meters."""
        chunk_size = max(1, chunk_size)
        pos = 0
        mv = memoryview(self._buf)
        try:
            while pos < self._effective_n():
                with self._cond:
                    self._start_locked()
                    while self._frontier_bytes() <= pos and \
                            pos < self._effective_n() and \
                            self._error is None:
                        # resubmit on every wake: budget-deferred
                        # stripes must go out the moment another
                        # read's completion frees tenant budget (the
                        # 50ms poll exists for exactly this)
                        self._submit_eligible_locked()
                        self._fire_hedges_locked()
                        self._cond.wait(self._wait_timeout_locked())
                    if self._error is not None:
                        raise self._error
                    upper = min(self._frontier_bytes(),
                                self._effective_n())
                while pos < upper:
                    n = min(chunk_size, upper - pos)
                    yield mv[pos:pos + n]
                    pos += n
                    with self._cond:
                        self._drained = pos
                        self._submit_eligible_locked()
        finally:
            with self._cond:
                if pos < self._effective_n() and self._error is None:
                    # consumer abandoned the read: stop the transfers
                    self._error = UnavailableError("read abandoned")
                    self._cancel_all_locked()
            self._close_span()


class RemoteReadRuntime:
    """Per-client runtime shared by all striped reads: the stripe
    executor, the rolling per-worker latency stats the hedger consults,
    and the conf.  Owned (and closed) by ``BlockStoreClient``."""

    def __init__(self, conf: Optional[RemoteReadConf] = None) -> None:
        from alluxio_tpu.qos import StripeBudget

        self.conf = conf or RemoteReadConf()
        self.stats = LatencyStats()
        #: tenant-scoped cap on concurrent stripe streams across every
        #: striped read in this runtime (atpu.user.qos.stripe.limit);
        #: the cap itself lives in the (swappable) conf, so retunes
        #: apply live
        self.budget = StripeBudget()
        self._ex: Optional[ThreadPoolExecutor] = None
        self._closed = False
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        return self.conf.enabled

    def executor(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._closed:
                # close() already drained; recreating here would leak
                # an executor no shutdown will ever see
                raise UnavailableError("remote-read runtime is closed")
            if self._ex is None:
                # room for a few concurrent striped reads plus their
                # hedges before attempts queue behind each other
                self._ex = ThreadPoolExecutor(
                    max_workers=max(8, self.conf.concurrency * 4),
                    thread_name_prefix="remote-read")
            return self._ex

    def read(self, *, block_id: int, sources: List[ReadSource],
             offset: int, length: int, chunk_size: int = 1 << 20,
             on_failed: Optional[Callable] = None) -> StripedRead:
        return StripedRead(self, block_id=block_id, sources=sources,
                           offset=offset, length=length,
                           chunk_size=chunk_size, on_failed=on_failed)

    def close(self) -> None:
        with self._lock:
            self._closed = True
            ex, self._ex = self._ex, None
        if ex is not None:
            ex.shutdown(wait=False)
