"""Filesystem client (reference: ``core/client``)."""

from alluxio_tpu.client.file_system import FileSystem  # noqa: F401
from alluxio_tpu.client.streams import (  # noqa: F401
    FileInStream, FileOutStream, ReadType, WriteType,
)
