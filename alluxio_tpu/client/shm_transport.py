"""Client side of the same-host zero-copy plane: SHM segment transport.

A co-located client leases a block's MEM-tier file from the worker
(``shm_open``), mmaps it ONCE, and serves every read of that block as a
``memoryview`` slice over the shared pages — zero RPCs, zero
serialization, zero copies per read. ``numpy_view`` hands the same pages
to ``np.frombuffer`` for a single ``jax.device_put`` (the only copy a
same-host read ever pays is host->device). See ``alluxio_tpu/shm/`` for
the lease protocol and docs/small_reads.md for the design.

The transport keeps an LRU **segment cache**
(``atpu.user.shm.segment.cache.max``): repeated opens of a hot block —
the shuffled-small-read pattern the subsystem exists for — cost a dict
hit, not an RPC. Leases renew *lazily*: a read touching a segment past
``atpu.user.shm.lease.renew.fraction`` of its TTL fires one
``shm_renew``, amortized over every read in between.

Failure contract (the fallback matrix in docs/small_reads.md): every
exit from this plane is a typed error the routing layer catches —
``ShmLeaseDeniedError`` / ``ShmSegmentUnavailableError`` from the
worker, ``OSError`` from a failed map (or the injected
``atpu.debug.fault.shm.map.error.rate``). A *renewal* failure on an
already-mapped segment is NOT an error: Linux keeps mmapped pages valid
across an unlink, so in-flight readers finish safely and only the next
cold open re-routes.
"""

from __future__ import annotations

import mmap
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

import numpy as np

from alluxio_tpu.client.block_streams import BlockInStream, _record_read
from alluxio_tpu.rpc.clients import WorkerClient


class ShmSegment:
    """One mapped segment: mmap + lease bookkeeping."""

    __slots__ = ("block_id", "path", "length", "lease_id", "ttl_s",
                 "renew_at", "mm", "dead")

    def __init__(self, block_id: int, path: str, length: int,
                 lease_id: int, ttl_s: float, renew_fraction: float,
                 mm: Optional[mmap.mmap]) -> None:
        self.block_id = block_id
        self.path = path
        self.length = length
        self.lease_id = lease_id
        self.ttl_s = ttl_s
        self.renew_at = time.monotonic() + ttl_s * renew_fraction
        self.mm = mm
        #: lease lost (renewal refused / released): serve existing maps,
        #: stop cache hits
        self.dead = False

    def view(self, offset: int = 0, length: int = -1) -> memoryview:
        if self.mm is None:
            return memoryview(b"")
        end = self.length if length < 0 else min(self.length,
                                                 offset + length)
        return memoryview(self.mm)[offset:max(offset, end)]

    def close_map(self) -> None:
        mm, self.mm = self.mm, None
        if mm is not None:
            try:
                mm.close()
            except BufferError:
                # a numpy view is still live (in-flight device_put);
                # leave the mapping to GC — pages stay valid on Linux
                pass


class ShmTransport:
    """Per-process segment cache + lease manager."""

    def __init__(self, session_id: int, *, cache_max: int = 64,
                 renew_fraction: float = 0.5, host: str = "",
                 native_fastpath: bool = True) -> None:
        self._session = session_id
        self._cache_max = max(1, int(cache_max))
        self._renew_fraction = min(0.95, max(0.05, float(renew_fraction)))
        self._host = host
        #: batch pread_many through the native plan executor
        #: (``atpu.user.native.fastpath.enabled``); the per-op Python
        #: loop stays as the byte-identical fallback
        self.native_fastpath = bool(native_fastpath)
        self._lock = threading.Lock()
        self._segments: "OrderedDict[int, ShmSegment]" = OrderedDict()

    # -------------------------------------------------------------- open
    def open_stream(self, worker: WorkerClient, block_id: int
                    ) -> "ShmBlockInStream":
        """The same-host read stream; raises the typed fallback errors
        (lease denied / segment unavailable / map OSError) the routing
        ladder in ``BlockStoreClient.open_block`` catches."""
        return ShmBlockInStream(self, worker, self.segment(worker,
                                                           block_id))

    def segment(self, worker: WorkerClient, block_id: int) -> ShmSegment:
        with self._lock:
            seg = self._segments.get(block_id)
            if seg is not None and not seg.dead:
                self._segments.move_to_end(block_id)
            else:
                seg = None
        if seg is not None:
            self._maybe_renew(worker, seg)
            if not seg.dead:
                return seg
            self.invalidate(block_id)
        return self._map(worker, block_id)

    def _map(self, worker: WorkerClient, block_id: int) -> ShmSegment:
        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.utils import faults
        from alluxio_tpu.utils.tracing import current_span

        sp = current_span()
        t0 = time.perf_counter()
        # lease grant: the worker pins the block against eviction before
        # we touch the file — typed denials propagate to the router
        lease = worker.shm_open(self._session, block_id)
        if sp is not None:
            sp.phase("lease_wait", (time.perf_counter() - t0) * 1000.0)
        t1 = time.perf_counter()
        try:
            if faults.armed() and \
                    faults.injector().take_shm_map_error(self._host):
                raise OSError(
                    f"injected shm map fault for block {block_id}")
            if lease["length"] > 0:
                f = open(lease["path"], "rb")
                try:
                    mm = mmap.mmap(f.fileno(), 0, prot=mmap.PROT_READ)
                finally:
                    f.close()
            else:
                mm = None
        except OSError:
            metrics().counter("Client.ShmMapFailures").inc()
            # we hold a lease we cannot use; give it back now rather
            # than waiting out the TTL
            try:
                worker.shm_release(self._session, lease["lease_id"])
            except Exception:  # noqa: BLE001 - TTL reclaims it anyway
                pass
            raise
        if sp is not None:
            sp.phase("shm_map", (time.perf_counter() - t1) * 1000.0)
        seg = ShmSegment(block_id, lease["path"], lease["length"],
                         lease["lease_id"], lease["ttl_s"],
                         self._renew_fraction, mm)
        victims = []
        with self._lock:
            self._segments[block_id] = seg
            self._segments.move_to_end(block_id)
            while len(self._segments) > self._cache_max:
                victims.append(self._segments.popitem(last=False)[1])
        for v in victims:
            self._release(worker, v)
        return seg

    # ------------------------------------------------------------- leases
    def _maybe_renew(self, worker: WorkerClient, seg: ShmSegment) -> None:
        """Lazy renewal: one RPC past the renew point, amortized over
        the zero-copy reads in between. A refused renewal (worker
        restarted, lease reclaimed) marks the segment dead — existing
        views stay valid (mmap semantics), the next open re-leases."""
        if seg.dead or time.monotonic() < seg.renew_at:
            return
        try:
            resp = worker.shm_renew(self._session, seg.lease_id)
        except Exception:  # noqa: BLE001 - worker gone: segment is stale
            seg.dead = True
            return
        if resp.get("ok"):
            seg.renew_at = time.monotonic() + \
                float(resp.get("ttl_s", seg.ttl_s)) * self._renew_fraction
        else:
            seg.dead = True

    def touch(self, worker: WorkerClient, seg: ShmSegment) -> None:
        """Read-path hook: keep the lease fresh while a stream serves."""
        self._maybe_renew(worker, seg)

    def _release(self, worker: Optional[WorkerClient],
                 seg: ShmSegment) -> None:
        seg.dead = True
        seg.close_map()
        if worker is not None:
            try:
                worker.shm_release(self._session, seg.lease_id)
            except Exception:  # noqa: BLE001 - TTL reclaims it anyway
                pass

    def invalidate(self, block_id: int) -> None:
        with self._lock:
            seg = self._segments.pop(block_id, None)
        if seg is not None:
            seg.dead = True
            seg.close_map()

    def close(self, worker_for=None) -> None:
        """Unmap everything; ``worker_for(block_id) -> WorkerClient``
        enables graceful lease release (else TTL expiry reclaims)."""
        with self._lock:
            segs = list(self._segments.values())
            self._segments.clear()
        for seg in segs:
            w = worker_for(seg.block_id) if worker_for is not None else None
            self._release(w, seg)

    def cached_blocks(self) -> int:
        with self._lock:
            return len(self._segments)


class ShmBlockInStream(BlockInStream):
    """Same-host zero-copy stream over a cached SHM segment.

    Reads are ``memoryview`` slices of shared pages: no RPC, no
    serialization — the read-path microscope shows zero ``serialize`` /
    ``wire`` phase time here, which `make bench-smallread` asserts."""

    source = "LOCAL"

    def __init__(self, transport: ShmTransport, worker: WorkerClient,
                 seg: ShmSegment) -> None:
        super().__init__(seg.block_id, seg.length)
        self.last_source = "SHM"
        self._transport = transport
        self._worker = worker
        self._seg = seg

    def pread(self, offset: int, n: int) -> bytes:
        self._transport.touch(self._worker, self._seg)
        out = bytes(self._seg.view(offset, n))
        from alluxio_tpu.metrics import metrics

        metrics().counter("Client.ShmReads").inc()
        _record_read("shm", len(out))
        return out

    def pread_view(self, offset: int, n: int) -> memoryview:
        """The zero-copy form of :meth:`pread`: a live view of the
        shared pages, no intermediate ``bytes``."""
        self._transport.touch(self._worker, self._seg)
        out = self._seg.view(offset, n)
        from alluxio_tpu.metrics import metrics

        metrics().counter("Client.ShmReads").inc()
        _record_read("shm", len(out))
        return out

    def pread_many(self, offsets, sizes):
        """Batched positioned reads: with the native fastpath on, the
        whole batch becomes ONE packed op table copied out of the
        mmapped segment GIL-free — zero per-op Python frames, one
        lease touch and one metrics update per batch instead of per
        op. Byte-identical per-op fallback on any native problem."""
        if self._transport.native_fastpath and len(offsets) > 1:
            from alluxio_tpu.client import fastpath

            if fastpath.available():
                try:
                    return self._native_pread_many(offsets, sizes)
                except fastpath.NativeExecError:
                    pass  # Client.NativeFallbacks already counted
            else:
                fastpath.note_unavailable()
        return super().pread_many(offsets, sizes)

    def _native_pread_many(self, offsets, sizes):
        from alluxio_tpu import native
        from alluxio_tpu.client import fastpath

        seg = self._seg
        self._transport.touch(self._worker, seg)
        offs = np.asarray(offsets, dtype=np.int64)
        szs = np.asarray(sizes, dtype=np.int64)
        if offs.size and int(offs.min()) < 0:
            # negative offsets hit memoryview's from-the-end slicing in
            # the per-op path; keep that quirk on the Python rung
            raise fastpath.NativeExecError("negative offset")
        # clamp exactly like ShmSegment.view: min(n, seg.length - off),
        # floored at zero (past-EOF and negative sizes read empty)
        lens = np.clip(np.minimum(szs, seg.length - offs), 0, None)
        bounds = np.zeros(offs.size + 1, dtype=np.int64)
        np.cumsum(lens, out=bounds[1:])
        dest = bytearray(int(bounds[-1]))
        if len(dest):
            loc = native._buffer_address(seg.mm) \
                if seg.mm is not None else None
            if loc is None:
                raise fastpath.NativeExecError("no segment address")
            addr, n, keep = loc
            ops = fastpath.op_table(offs.size)
            ops["src"] = addr  # kind zero-init == OP_COPY
            ops["src_off"] = offs.astype(np.uint64)
            ops["src_len"] = n
            ops["dst_off"] = bounds[:-1]
            ops["len"] = lens
            fastpath.execute_table(ops, dest, host="shm")
            del keep
        from alluxio_tpu.client.block_streams import _metrics

        m = _metrics()
        m.counter("Client.ShmReads").inc(offs.size)
        m.counter("Client.BytesRead.shm").inc(len(dest))
        m.counter("Client.BlocksRead.shm").inc(offs.size)
        return fastpath.slice_out(dest, bounds.tolist())

    def memoryview(self) -> Optional[memoryview]:
        return self._seg.view()

    def numpy_view(self, dtype=np.uint8) -> np.ndarray:
        """Zero-copy ndarray over the shared pages — feed straight to
        ``jax.device_put`` (the DLPack/``np.frombuffer`` handoff)."""
        if self._seg.mm is None:
            return np.empty(0, dtype=dtype)
        from alluxio_tpu.metrics import metrics

        metrics().counter("Client.ShmReads").inc()
        _record_read("shm", self._seg.length)
        return np.frombuffer(self._seg.mm, dtype=dtype)

    def close(self) -> None:
        # the segment stays cached (and leased) for the next open — the
        # whole point of the transport; BlockStoreClient.close releases
        pass
