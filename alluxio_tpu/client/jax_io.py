"""Zero-copy JAX read path: cached blocks -> device arrays.

**The TPU-native replacement for the reference's FUSE data path**
(BASELINE.json north star: replace ``integration/fuse`` -> page cache ->
``cudaMemcpy`` with cached blocks materializing as ``jax.Array``). Ladder
per block:

1. **HBM hit** — the block is already device-resident in the HBM page
   store: the "read" returns the live ``jax.Array``; no host traffic at
   all.
2. **Host hit (short-circuit)** — block cached on a same-host worker in
   /dev/shm: mmap -> zero-copy numpy view -> ``jax.device_put`` (one DMA,
   no intermediate copy), then the HBM store retains it for next epoch.
3. **Cold** — worker read-through from the UFS (caching it), then (2).

``device_put`` dispatches asynchronously, so the loader keeps
``prefetch`` transfers in flight while the consumer computes — the
double-buffering that hides H2D latency behind step time (SURVEY.md hard
part: "prefetch collectives must overlap compute").
"""

from __future__ import annotations

import threading
import weakref
from collections import deque
from concurrent.futures import CancelledError
from concurrent.futures import TimeoutError as FuturesTimeoutError
from typing import Iterator, List, Optional, Sequence

import numpy as np

from alluxio_tpu.client.cache.hbm_store import HbmPageStore
from alluxio_tpu.client.cache.meta import PageId
from alluxio_tpu.client.file_system import FileSystem
from alluxio_tpu.conf import Keys
from alluxio_tpu.metrics import metrics
from alluxio_tpu.metrics.stall import (BUCKET_ADVICE, SIZE_BUCKETS,
                                       STALL_BUCKETS, size_bucket)
from alluxio_tpu.utils.tracing import annotate, current_span


#: live StepStats instances backing the ONE process-level
#: Client.InputBoundFraction gauge — per-instance registration would
#: let a closed loader's frozen fraction shadow the running one (and
#: pin the dead loader via the registry's closure)
_LIVE_STEP_STATS: "weakref.WeakSet" = None  # type: ignore[assignment]
_GAUGE_LOCK = threading.Lock()


def _process_input_bound_fraction() -> float:
    with _GAUGE_LOCK:
        # copy under the lock: a concurrent StepStats.__init__ add()
        # mid-iteration raises "set changed size during iteration"
        stats = list(_LIVE_STEP_STATS or ())
    if not stats:
        return 0.0
    wait = elapsed = 0.0
    for st in stats:
        w, e = st.window_totals()
        wait += w
        elapsed += e
    return (wait / elapsed) if elapsed > 0 else 0.0


class StepStats:
    """Input-stall attribution for one :class:`DeviceBlockLoader`.

    Every time the consumer waits on the loader pipeline, the wait is
    attributed to the serving tier of the block that eventually arrived.
    Exports ``Client.InputStall.<bucket>`` timers (local percentiles),
    additive ``Client.InputStallUs/Count/Bytes.<bucket>`` counters (they
    roll up to ``Cluster.*`` on the metrics heartbeat), and a rolling
    input-bound-fraction gauge — what ``fsadmin report stall``, the
    master statuspage and the stress suite read."""

    def __init__(self, window: int = 512) -> None:
        global _LIVE_STEP_STATS

        self._lock = threading.Lock()
        self._m = metrics()
        self.wait_s = {b: 0.0 for b in STALL_BUCKETS}
        self.count = {b: 0 for b in STALL_BUCKETS}
        self.bytes = {b: 0 for b in STALL_BUCKETS}
        # op-size attribution alongside the tier attribution: a stall
        # profile dominated by le4k ops is per-op RPC overhead, not
        # bandwidth — different fix, so it gets its own columns
        self.size_wait_s = {b: 0.0 for b in SIZE_BUCKETS}
        self.size_count = {b: 0 for b in SIZE_BUCKETS}
        self.size_bytes = {b: 0 for b in SIZE_BUCKETS}
        # the tier x size cross: "le4k stalls" alone doesn't say whether
        # the small reads were already on the SHM plane (compute-bound,
        # nothing to turn) or still paying remote RPCs (enable batching
        # / co-locate) — fsadmin report stall renders this split
        self.cross_wait_s = {(t, s): 0.0 for t in STALL_BUCKETS
                             for s in SIZE_BUCKETS}
        self.cross_count = {(t, s): 0 for t in STALL_BUCKETS
                            for s in SIZE_BUCKETS}
        #: rolling (wait_s, elapsed_s) per consumed block — the gauge's
        #: window, so the fraction tracks NOW, not the whole run
        self._window: deque = deque(maxlen=window)
        with _GAUGE_LOCK:
            if _LIVE_STEP_STATS is None:
                _LIVE_STEP_STATS = weakref.WeakSet()
            _LIVE_STEP_STATS.add(self)
        # one registration for the whole process (idempotent overwrite
        # of the same function): the gauge pools LIVE collectors only
        self._m.register_gauge("Client.InputBoundFraction",
                               _process_input_bound_fraction)

    def close(self) -> None:
        """Drop this collector from the process gauge (its additive
        counters keep their totals — only the live fraction stops)."""
        with _GAUGE_LOCK:
            if _LIVE_STEP_STATS is not None:
                _LIVE_STEP_STATS.discard(self)

    def window_totals(self) -> "tuple[float, float]":
        """(waited_s, elapsed_s) over the rolling window."""
        with self._lock:
            return (sum(w for w, _ in self._window),
                    sum(e for _, e in self._window))

    def record(self, bucket: str, wait_s: float, nbytes: int,
               elapsed_s: float) -> None:
        if bucket not in self.wait_s:
            bucket = "unknown"
        sb = size_bucket(nbytes)
        with self._lock:
            self.wait_s[bucket] += wait_s
            self.count[bucket] += 1
            self.bytes[bucket] += nbytes
            self.size_wait_s[sb] += wait_s
            self.size_count[sb] += 1
            self.size_bytes[sb] += nbytes
            self.cross_wait_s[(bucket, sb)] += wait_s
            self.cross_count[(bucket, sb)] += 1
            self._window.append((wait_s, max(elapsed_s, wait_s)))
        self._m.timer(f"Client.InputStall.{bucket}").update(wait_s)
        self._m.counter(f"Client.InputStallSizeUs.{sb}").inc(
            int(wait_s * 1e6))
        self._m.counter(f"Client.InputStallSizeCount.{sb}").inc()
        self._m.counter(f"Client.InputStallUs.{bucket}").inc(
            int(wait_s * 1e6))
        self._m.counter(f"Client.InputStallCount.{bucket}").inc()
        self._m.counter(f"Client.InputStallBytes.{bucket}").inc(nbytes)
        # the tier x size cross (additive, rolls up to Cluster.*):
        # fsadmin report stall cuts the le4k row by these to show
        # whether small reads ride shm / remote / ufs
        self._m.counter(f"Client.InputStallCrossUs.{bucket}.{sb}").inc(
            int(wait_s * 1e6))
        self._m.counter(
            f"Client.InputStallCrossCount.{bucket}.{sb}").inc()

    def input_bound_fraction(self) -> float:
        """Share of recent wall time the consumer spent waiting for
        input (0 = compute-bound, 1 = fully input-bound)."""
        wait, elapsed = self.window_totals()
        return (wait / elapsed) if elapsed > 0 else 0.0

    def report(self) -> dict:
        """Ranked bottleneck verdict (the input doctor)."""
        with self._lock:
            wait = dict(self.wait_s)
            count = dict(self.count)
            nbytes = dict(self.bytes)
            s_wait = dict(self.size_wait_s)
            s_count = dict(self.size_count)
            s_bytes = dict(self.size_bytes)
            x_wait = dict(self.cross_wait_s)
            x_count = dict(self.cross_count)
        total = sum(wait.values())
        buckets = {}
        for b in STALL_BUCKETS:
            if not count[b]:
                continue
            buckets[b] = {
                "wait_s": round(wait[b], 6), "count": count[b],
                "bytes": nbytes[b],
                "share": round(wait[b] / total, 4) if total else 0.0,
            }
        ranked = sorted(buckets, key=lambda b: buckets[b]["wait_s"],
                        reverse=True)
        frac = self.input_bound_fraction()
        if not ranked:
            verdict = "no input-stall samples recorded"
        else:
            top = ranked[0]
            verdict = (f"input-bound {frac:.0%} of recent wall time; "
                       f"top bottleneck: {top} "
                       f"({buckets[top]['share']:.0%} of "
                       f"{total:.3f}s stall) — {BUCKET_ADVICE[top]}")
        size_buckets = {}
        for b in SIZE_BUCKETS:
            if not s_count[b]:
                continue
            # per-size tier split: which plane the ops of this size rode
            # (the le4k row is how you read "did batching/SHM land?")
            by_source = {}
            for t in STALL_BUCKETS:
                if not x_count[(t, b)]:
                    continue
                by_source[t] = {
                    "wait_s": round(x_wait[(t, b)], 6),
                    "count": x_count[(t, b)],
                    "share": round(x_wait[(t, b)] / s_wait[b], 4)
                    if s_wait[b] else 0.0,
                }
            size_buckets[b] = {
                "wait_s": round(s_wait[b], 6), "count": s_count[b],
                "bytes": s_bytes[b],
                "share": round(s_wait[b] / total, 4) if total else 0.0,
                "by_source": by_source,
            }
        return {"total_wait_s": round(total, 6),
                "input_bound_fraction": round(frac, 4),
                "buckets": buckets, "ranked": ranked,
                "size_buckets": size_buckets,
                "verdict": verdict}


class DeviceBlockLoader:
    """Loads whole blocks of one or more files as device-resident uint8
    arrays, with an HBM retention cache and transfer prefetch."""

    def __init__(self, fs: FileSystem, paths: Sequence[str], *,
                 device=None, hbm_bytes: int = 0,
                 prefetch: Optional[int] = None, dtype=np.uint8,
                 prefetch_service=None) -> None:
        import jax

        self._jax = jax
        self._fs = fs
        self._dtype = np.dtype(dtype)
        self._device = device or jax.devices()[0]
        self._hbm = HbmPageStore(hbm_bytes, self._device) \
            if hbm_bytes > 0 else None
        if prefetch is None:
            # double-buffer depth for the zero-copy iterator
            # (atpu.tpu.prefetch.buffer.batches, default 2)
            prefetch = fs._conf.get_int(Keys.TPU_PREFETCH_BUFFER_BATCHES)
        self._prefetch = max(0, prefetch)
        # clairvoyant prefetch service (prefetch/service.py). None (the
        # default) leaves every code path byte-identical to a loader
        # without the subsystem; set, the loader consumes epochs in the
        # oracle's seeded order, registers its cursor via on_consume,
        # and records hit/late/miss outcomes.
        self._svc = prefetch_service
        self._epoch_counter = 0
        self._m = metrics()
        #: input doctor: per-tier wait attribution for this loader
        self.step_stats = StepStats()
        #: flat list of (path, block_index, page_id)
        self._plan: List[tuple] = []
        #: path -> master block ids (public: saves consumers a
        #: get_status round-trip per path, e.g. placement reporting)
        self.block_ids_by_path: dict = {}
        self._infos = {}
        # the prefetch service already resolved these paths for its
        # manifest: reuse those FileInfos rather than paying a second
        # get_status round per file on the startup path
        resolved = dict(prefetch_service.oracle.manifest.file_infos) \
            if prefetch_service is not None else {}
        for path in paths:
            info = resolved.get(str(path)) or fs.get_status(path)
            self._infos[path] = info
            self.block_ids_by_path[path] = list(info.block_ids)
            for i in range(len(info.block_ids)):
                self._plan.append((path, i, PageId(f"{info.file_id:x}", i)))
        # streams are per-thread: FileInStream holds per-block state, so
        # concurrent host_block callers (mesh load thread pool) must not
        # share one (close()-races would silently yield empty views)
        self._tls = threading.local()
        self._all_streams: List = []
        self._streams_lock = threading.Lock()
        #: the producer thread's stream cache, published in its finally
        #: so early-exit retirement can close it from the consumer side
        self._producer_streams = None
        # ONE persistent producer thread across epochs: a fresh thread
        # per epoch would miss the thread-local stream cache and reopen
        # every stream each epoch (fd/mmap leak over a training run)
        self._producer_pool = None
        # at most one live epoch: starting a new one (or close()) cancels
        # the previous producer, else an abandoned-but-referenced
        # generator parks the single producer thread forever and
        # close()/the next epoch() deadlock behind it
        self._epoch_lock = threading.Lock()
        self._current_stop: Optional[threading.Event] = None
        self._closed = False
        # warm the native layer at construction: its first use may g++
        # -compile the .so, which must not land on the epoch hot path
        from alluxio_tpu import native as _native

        _native.lib()
        if self._svc is not None and self._hbm is not None:
            # the agent's HBM placements ride this loader's host-read
            # path and page store (device_put dispatches async, so the
            # agent tick stays short)
            self._svc.bind_hbm(self.prefetch_into_hbm)

    def __len__(self) -> int:
        return len(self._plan)

    @property
    def plan(self) -> List[tuple]:
        """The load plan as public ``(path, block_index)`` pairs (the
        mesh data plane builds its placement from this)."""
        return [(path, i) for (path, i, _pid) in self._plan]

    def host_block(self, path: str, index: int):
        """Public host-side read of one block (zero-copy numpy view on the
        short-circuit path, else a streamed copy)."""
        return self._host_bytes(path, index)

    # -- single block --------------------------------------------------------
    def _host_bytes(self, path: str, index: int):
        """Host-side view of one block: zero-copy numpy over mmap when the
        short-circuit path applies, else a bytes copy from the stream."""
        streams = getattr(self._tls, "streams", None)
        if streams is None:
            streams = self._tls.streams = {}
        f = streams.get(path)
        if f is None:
            # one cached block stream per file: the loader holds a
            # stream per (thread, path) for its whole life, so a larger
            # cache would multiply worker-side block pins
            f = self._fs.open_file(path, info=self._infos.get(path),
                                   max_open_streams=1)
            with self._streams_lock:
                # closed-check INSIDE the lock: an agent thread (HBM
                # adopt) racing close() must not register a stream
                # after close() swept _all_streams — that stream would
                # leak (with its worker-side pins) for process lifetime
                if self._closed:
                    f.close()
                    raise RuntimeError("loader is closed")
                self._all_streams.append(f)
            streams[path] = f
        stream = f.block_stream(index)
        view = getattr(stream, "numpy_view", None)
        if view is not None:
            self._m.counter("Client.JaxShortCircuitBlocks").inc()
            self._tls.last_bucket = "shm"
            return view(dtype=self._dtype)
        self._m.counter("Client.JaxStreamedBlocks").inc()
        # striped remote reads expose their assembly buffer as a view:
        # frombuffer wraps it zero-copy, so the bytes go straight from
        # the stripe streams into device_put with no join pass
        reader = getattr(stream, "read_all_view", None)
        buf = reader() if reader is not None else stream.read_all()
        data = np.frombuffer(buf, dtype=self._dtype)
        # AFTER the read: a stale location can self-heal into a UFS
        # read-through mid-call, and only the stream knows what served
        self._tls.last_bucket = stream.source_bucket()
        return data

    def prefetch_into_hbm(self, ref) -> bool:
        """Prefetch-agent hook: host-read one block and adopt it into
        the HBM tier ahead of its consume (runs on the agent's heartbeat
        thread; per-thread streams keep it off the producer's state)."""
        if self._hbm is None or self._closed:
            return False
        info = self._infos.get(ref.path)
        fid = info.file_id if info is not None else ref.file_id
        pid = PageId(f"{fid:x}", ref.block_index)
        if self._hbm.has(pid):
            return True
        host = self._host_bytes(ref.path, ref.block_index)
        arr = self._jax.device_put(host, self._device)
        return self._hbm.adopt(pid, arr)

    def load_block(self, plan_index: int):
        """One block as a device uint8 array (HBM-cached across epochs)."""
        if self._closed:
            raise RuntimeError("loader is closed")
        path, index, pid = self._plan[plan_index]
        if self._hbm is not None:
            lease = self._hbm.get(pid)
            if lease is not None:
                self._m.counter("Client.JaxHbmHits").inc()
                arr = lease.array
                # safe to unpin before returning: eviction only drops the
                # store's reference (never arr.delete()), so the array the
                # consumer holds stays valid regardless
                lease.close()
                return arr
        host = self._host_bytes(path, index)
        arr = self._jax.device_put(host, self._device)
        if self._hbm is not None:
            self._hbm.adopt(pid, arr)  # no second transfer
        return arr

    # -- iteration -----------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self.epoch()

    def _epoch_entries(self, epoch_no: int) -> List[tuple]:
        """The per-epoch load plan as ``(path, index, pid, ref)`` rows.
        Without a prefetch service the order is the static file order
        (``ref`` None, behavior identical to pre-service builds); with
        one, it is the oracle's seeded permutation for this epoch."""
        if self._svc is None:
            return [(p, i, pid, None) for (p, i, pid) in self._plan]
        entries = []
        for ref in self._svc.epoch_sequence(epoch_no):
            info = self._infos.get(ref.path)
            fid = info.file_id if info is not None else ref.file_id
            entries.append((ref.path, ref.block_index,
                            PageId(f"{fid:x}", ref.block_index), ref))
        return entries

    def epoch(self) -> Iterator:
        """Iterate all blocks as device arrays with transfer prefetch.

        Two-stage pipeline: a producer thread does ALL host-side work
        (worker RPCs, mmap setup, page pre-fault) ahead of the consumer,
        so the device_put stream never stalls on per-block host latency
        — that serialization was the measured ~25% gap between the
        loader and the raw device_put ceiling. The queue is bounded, and
        an abandoned generator unblocks the producer via a stop flag.

        Early consumer exit (break mid-epoch) retires the producer
        executor: the queue is drained, the producer's streams closed,
        and the ``loader-host-prefetch`` thread joined before control
        returns — nothing leaks waiting for ``close()``."""
        import time as _time
        import queue as _q

        q: _q.Queue = _q.Queue(maxsize=max(1, self._prefetch) + 1)
        stop = threading.Event()
        retire = threading.Event()
        SENTINEL = object()

        def producer(entries, gen):
            try:
                for (path, index, pid, ref) in entries:
                    if stop.is_set():
                        return
                    if self._hbm is not None:
                        lease = self._hbm.get(pid)
                        if lease is not None:
                            self._m.counter("Client.JaxHbmHits").inc()
                            arr = lease.array
                            lease.close()
                            if ref is not None:
                                out = self._svc.on_consume(
                                    ref, resident_hint=True,
                                    generation=gen)
                                if out != "stale":
                                    self._svc.release(ref)
                            self._put(q, stop, (pid, arr, True, "hbm",
                                                getattr(arr, "nbytes", 0)))
                            continue
                    outcome = None
                    if ref is not None:
                        # classify BEFORE the read (ready state decides
                        # hit vs late); the eviction pin is released
                        # only after the read holds its own block lock.
                        # The generation fences a superseded producer's
                        # last consume off the new epoch's cursor.
                        outcome = self._svc.on_consume(ref,
                                                       generation=gen)
                        t0 = _time.monotonic()
                    with annotate("atpu.loader.host_read"):
                        host = self._host_bytes(path, index)
                        if host.size:
                            # pre-fault mmap pages off the transfer
                            # thread's clock (native: GIL-free touch)
                            from alluxio_tpu import native

                            if not native.prefault(host):
                                host[::4096].max()
                    bucket = getattr(self._tls, "last_bucket", "unknown")
                    if ref is not None:
                        if outcome != "stale":
                            # a stale (superseded-epoch) consume must
                            # NOT release: the scheduler still counts
                            # the block ready, and the pin is what
                            # keeps that true — the new epoch's own
                            # consume releases it
                            self._svc.release(ref)
                        if outcome not in ("hit", "stale"):
                            # block-ready stall: how long the consumer
                            # waited for data clairvoyance should have
                            # had resident already
                            self._svc.record_stall(
                                _time.monotonic() - t0)
                    self._put(q, stop, (pid, host, False, bucket,
                                        host.nbytes))
            except BaseException as e:  # noqa: BLE001 re-raised in consumer
                # a read failure must FAIL the epoch, not silently end
                # it short (a truncated epoch looks complete downstream)
                self._put(q, stop, ("__error__", e))
            finally:
                self._put(q, stop, SENTINEL)
                # publish this thread's stream cache: if the consumer
                # retires the pool AFTER we already exited (late break),
                # it closes these post-join — retire.is_set() here alone
                # would race and leak them until loader.close()
                self._producer_streams = getattr(self._tls, "streams",
                                                 None)
                if retire.is_set():
                    self._close_streams_dict(self._producer_streams)

        with self._epoch_lock:
            if self._closed:
                # a pre-close generator first iterated after close()
                # must not silently resurrect the pool/streams
                raise RuntimeError("loader is closed")
            if self._current_stop is not None:
                self._current_stop.set()
            self._current_stop = stop
            if self._producer_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._producer_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="loader-host-prefetch")
            epoch_no = self._epoch_counter
            self._epoch_counter += 1
            gen = self._svc.begin_epoch(epoch_no) \
                if self._svc is not None else None
            fut = self._producer_pool.submit(producer,
                                             self._epoch_entries(epoch_no),
                                             gen)
        inflight: deque = deque()
        finished = False
        try:
            # input-doctor accounting: each queue wait is attributed to
            # the serving tier of the item that ends it; elapsed-since-
            # last-item bounds the rolling input-bound fraction
            last_item_t = _time.monotonic()
            while True:
                wait_t0 = _time.monotonic()
                while True:
                    try:
                        item = q.get(timeout=0.5)
                        break
                    except _q.Empty:
                        if stop.is_set():
                            # cancelled by close()/a newer epoch(): fail
                            # loudly — a silently-truncated epoch looks
                            # complete downstream
                            raise RuntimeError(
                                "epoch cancelled: the loader was closed "
                                "or a newer epoch() superseded this "
                                "iterator")
                if item is SENTINEL:
                    break
                if item[0] == "__error__":
                    raise item[1]
                pid, data, on_device, bucket, nbytes = item
                now = _time.monotonic()
                self.step_stats.record(bucket, now - wait_t0, nbytes,
                                       now - last_item_t)
                last_item_t = now
                outer = current_span()
                if outer is not None:
                    # consumer-side pipeline wait: the time this step
                    # spent blocked on the producer queue
                    outer.phase("drain", (now - wait_t0) * 1000.0)
                if on_device:
                    arr = data
                else:
                    with annotate("atpu.loader.h2d"):
                        sp = current_span()
                        if sp is None:
                            arr = self._jax.device_put(data, self._device)
                        else:
                            t_put = _time.perf_counter()
                            arr = self._jax.device_put(data, self._device)
                            sp.phase("device_put",
                                     (_time.perf_counter() - t_put)
                                     * 1000.0)
                    if self._hbm is not None:
                        self._hbm.adopt(pid, arr)  # no second transfer
                inflight.append(arr)
                while len(inflight) > self._prefetch:
                    yield inflight.popleft()
            while inflight:
                yield inflight.popleft()
            finished = True
        finally:
            with self._epoch_lock:
                # superseded by a newer epoch() or close()?
                cancelled = self._current_stop is not stop
                closed = self._closed
            # early consumer exit (break / .close() mid-epoch) on the
            # LIVE epoch: retire the producer executor entirely — the
            # producer closes its per-thread streams on the way out and
            # the pool thread is joined below, so nothing waits for
            # loader.close() to stop leaking
            early_exit = not finished and not cancelled and not closed
            if early_exit:
                retire.set()
            stop.set()
            self._drain(q)  # unblock a producer parked on the full queue
            try:
                fut.result(timeout=5)
            except CancelledError:  # close() shut the pool first
                pass
            except (TimeoutError, FuturesTimeoutError):
                # (both spellings: distinct classes before python 3.11)
                if not cancelled:
                    # a live epoch's producer is wedged (e.g. hung
                    # worker RPC): surface it, don't mask the hang
                    raise
            # one last put can land between the first drain and the
            # producer observing stop: drain again now that it exited
            self._drain(q)
            if early_exit:
                with self._epoch_lock:
                    pool = None
                    if self._current_stop is stop:
                        self._current_stop = None
                        pool, self._producer_pool = \
                            self._producer_pool, None
                if pool is not None:
                    pool.shutdown(wait=True)
                    # the producer may have finished before retire was
                    # set; its published stream cache is closed here
                    # (idempotent: the dict is cleared on first close)
                    self._close_streams_dict(
                        getattr(self, "_producer_streams", None))

    @staticmethod
    def _drain(q) -> None:
        import queue as _q

        while True:
            try:
                q.get_nowait()
            except _q.Empty:
                break

    def _close_streams_dict(self, streams) -> None:
        """Close a (retiring) thread's cached block streams — they must
        not linger until loader.close(). Clears the dict in place, so a
        second call (producer-side AND consumer-side retirement paths)
        is a no-op."""
        if not streams:
            return
        victims = list(streams.values())
        streams.clear()
        with self._streams_lock:
            for f in victims:
                if f in self._all_streams:
                    self._all_streams.remove(f)
        for f in victims:
            f.close()

    @staticmethod
    def _put(q, stop, item) -> None:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except Exception:  # noqa: BLE001 queue.Full
                continue

    def hbm_stats(self) -> dict:
        if self._hbm is None:
            return {"hbm_bytes": 0}
        return {"hbm_bytes": self._hbm.used_bytes,
                "hbm_pages": self._hbm.page_count}

    def stall_report(self) -> dict:
        """Input-doctor verdict: ranked per-tier wait attribution for
        this loader (see :meth:`StepStats.report`)."""
        return self.step_stats.report()

    def close(self) -> None:
        self.step_stats.close()  # stop feeding the process gauge
        if self._svc is not None:
            self._svc.bind_hbm(None)  # agent must not touch a dead loader
        with self._epoch_lock:
            self._closed = True
            if self._current_stop is not None:
                self._current_stop.set()  # unblock a parked producer
                self._current_stop = None
            pool, self._producer_pool = self._producer_pool, None
        if pool is not None:
            pool.shutdown(wait=True)
        with self._streams_lock:
            # under the lock: serializes with _host_bytes' registration
            # (an in-flight HBM adopt either lands its stream here and
            # we close it, or observes _closed and closes it itself)
            for f in self._all_streams:
                f.close()
            self._all_streams.clear()
        if self._hbm is not None:
            self._hbm.close()


def batched_device_iterator(loader: DeviceBlockLoader, *, record_bytes: int,
                            batch_size: int, drop_remainder: bool = True):
    """Group fixed-size records from block arrays into batches on device.

    The reshape happens in a jitted fn so XLA fuses it with whatever decode
    follows; records must not straddle blocks (the writer pads — same
    contract as TFRecord sharding)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def to_records(block):
        n = block.shape[0] // record_bytes
        return block[:n * record_bytes].reshape(n, record_bytes)

    pending = None
    for block in loader.epoch():
        recs = to_records(block)
        if pending is not None:
            recs = jnp.concatenate([pending, recs], axis=0)
            pending = None
        n_full = recs.shape[0] // batch_size
        for b in range(n_full):
            yield recs[b * batch_size:(b + 1) * batch_size]
        rem = recs.shape[0] % batch_size
        if rem:
            pending = recs[-rem:]
    if pending is not None and not drop_remainder:
        yield pending
