"""Block location policies: which worker serves/stores a block.

Re-design of ``core/client/fs/src/main/java/alluxio/client/block/policy/
{BlockLocationPolicy,LocalFirstPolicy,LocalFirstAvoidEvictionPolicy,
MostAvailableFirstPolicy,RoundRobinPolicy,DeterministicHashPolicy,
SpecificHostPolicy}.java`` — with TPU locality: "local first" means same
host (shm short-circuit), then same ICI slice, then pod, then DCN
(``TieredIdentity`` ordering re-mapped in ``utils/wire.py``).
"""

from __future__ import annotations

import hashlib
import itertools
import random
from typing import List, Optional

from alluxio_tpu.utils.wire import TieredIdentity, WorkerInfo, WorkerNetAddress


class BlockLocationPolicy:
    def pick(self, workers: List[WorkerInfo], *, block_id: int = 0,
             block_size: int = 0) -> Optional[WorkerNetAddress]:
        raise NotImplementedError

    @staticmethod
    def create(kind: str, *, identity: Optional[TieredIdentity] = None,
               **kwargs) -> "BlockLocationPolicy":
        k = kind.upper()
        if k == "LOCAL_FIRST":
            return LocalFirstPolicy(identity or TieredIdentity([]))
        if k == "LOCAL_FIRST_AVOID_EVICTION":
            return LocalFirstAvoidEvictionPolicy(identity or TieredIdentity([]))
        if k == "MOST_AVAILABLE":
            return MostAvailablePolicy()
        if k == "ROUND_ROBIN":
            return RoundRobinPolicy()
        if k == "DETERMINISTIC_HASH":
            return DeterministicHashPolicy(**kwargs)
        if k == "SPECIFIC_HOST":
            return SpecificHostPolicy(**kwargs)
        raise ValueError(f"unknown policy {kind}")


class LocalFirstPolicy(BlockLocationPolicy):
    """Nearest by TieredIdentity; random among equally-near
    (reference: ``LocalFirstPolicy.java``)."""

    def __init__(self, identity: TieredIdentity) -> None:
        self._id = identity
        self._rng = random.Random()

    def pick(self, workers: List[WorkerInfo], *, block_id: int = 0,
             block_size: int = 0) -> Optional[WorkerNetAddress]:
        if not workers:
            return None
        scored = [(self._id.closeness(w.address.tiered_identity), i)
                  for i, w in enumerate(workers)]
        best = min(s for s, _ in scored)
        near = [workers[i] for s, i in scored if s == best]
        return self._rng.choice(near).address


class LocalFirstAvoidEvictionPolicy(BlockLocationPolicy):
    """Local first, but skip workers whose free space < block size
    (reference: ``LocalFirstAvoidEvictionPolicy``)."""

    def __init__(self, identity: TieredIdentity) -> None:
        self._inner = LocalFirstPolicy(identity)

    def pick(self, workers: List[WorkerInfo], *, block_id: int = 0,
             block_size: int = 0) -> Optional[WorkerNetAddress]:
        roomy = [w for w in workers
                 if w.capacity_bytes - w.used_bytes >= block_size]
        return self._inner.pick(roomy or workers, block_id=block_id,
                                block_size=block_size)


class MostAvailablePolicy(BlockLocationPolicy):
    def pick(self, workers: List[WorkerInfo], *, block_id: int = 0,
             block_size: int = 0) -> Optional[WorkerNetAddress]:
        if not workers:
            return None
        return max(workers,
                   key=lambda w: w.capacity_bytes - w.used_bytes).address


class RoundRobinPolicy(BlockLocationPolicy):
    def __init__(self) -> None:
        self._counter = itertools.count()

    def pick(self, workers: List[WorkerInfo], *, block_id: int = 0,
             block_size: int = 0) -> Optional[WorkerNetAddress]:
        if not workers:
            return None
        ordered = sorted(workers, key=lambda w: w.address.key())
        return ordered[next(self._counter) % len(ordered)].address


class DeterministicHashPolicy(BlockLocationPolicy):
    """Hash the block id onto k candidate workers, then choose among them —
    spreads cold UFS reads of one block over exactly k workers cluster-wide
    (reference: ``DeterministicHashPolicy``; SURVEY 2.11 'parallel UFS
    reads')."""

    def __init__(self, shards: int = 1) -> None:
        self._shards = max(1, shards)
        self._rng = random.Random()

    def pick(self, workers: List[WorkerInfo], *, block_id: int = 0,
             block_size: int = 0) -> Optional[WorkerNetAddress]:
        if not workers:
            return None
        ordered = sorted(workers, key=lambda w: w.address.key())
        digest = hashlib.md5(str(block_id).encode()).digest()
        start = int.from_bytes(digest[:8], "big")
        candidates = [ordered[(start + i) % len(ordered)]
                      for i in range(min(self._shards, len(ordered)))]
        return self._rng.choice(candidates).address


class SpecificHostPolicy(BlockLocationPolicy):
    def __init__(self, hostname: str = "") -> None:
        self._host = hostname

    def pick(self, workers: List[WorkerInfo], *, block_id: int = 0,
             block_size: int = 0) -> Optional[WorkerNetAddress]:
        for w in workers:
            if w.address.host == self._host or \
                    w.address.tiered_identity.value("host") == self._host:
                return w.address
        return None
