"""Public FileSystem client API.

Re-design of ``core/client/fs/src/main/java/alluxio/client/file/
{FileSystem.java:79,BaseFileSystem.java:92,FileSystemContext.java:91}``:
one facade over the master clients + block store, with an optional
client-side metadata cache (``MetadataCachingBaseFileSystem``) and the
config-hash live-reinit handshake (``FileSystemContextReinitializer.java:44``).
"""

from __future__ import annotations

import socket
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

from alluxio_tpu.client.block_store import BlockStoreClient
from alluxio_tpu.client.block_streams import BatchReadConf
from alluxio_tpu.client.policy import BlockLocationPolicy
from alluxio_tpu.client.remote_read import RemoteReadConf
from alluxio_tpu.client.streams import FileInStream, FileOutStream, WriteType
from alluxio_tpu.conf import Configuration, Keys
from alluxio_tpu.rpc.clients import (
    BlockMasterClient, FsMasterClient, MetaMasterClient,
)
from alluxio_tpu.utils.exceptions import best_effort
from alluxio_tpu.utils.uri import AlluxioURI
from alluxio_tpu.utils.wire import FileInfo, MountPointInfo, TieredIdentity


class _MetadataCache:
    """Bounded-LRU path -> FileInfo / listing cache with master-pushed
    invalidation (reference: ``client/file/MetadataCache.java`` is
    TTL-only; here every GetStatus/ListStatus response carries a
    version stamp from the master's invalidation log and the metrics
    heartbeat delivers invalidated path-prefixes, so a warm entry stays
    coherent within one heartbeat interval — docs/metadata.md.  TTL
    remains the belt-and-braces bound for partitioned clients).

    Thread-safe: the heartbeat thread applies pushes while reader
    threads hit the cache."""

    #: listings live under ``path + _LIST`` so path-prefix invalidation
    #: naturally covers them
    _LIST = "\0list"

    def __init__(self, max_size: int, ttl_s: float) -> None:
        self._max = max_size
        self._ttl = ttl_s
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        self._lock = threading.Lock()
        #: highest master invalidation-log version applied here (None
        #: until the first heartbeat establishes the floor)
        self.applied_version: Optional[int] = None

    # -- reads --------------------------------------------------------------
    def get(self, path: str) -> Optional[FileInfo]:
        return self._get(path)

    def get_listing(self, path: str) -> Optional[List[FileInfo]]:
        return self._get(path + self._LIST)

    def _get(self, key: str):
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                return None
            value, expiry, _stamp = e
            if time.monotonic() > expiry:
                del self._entries[key]
                return None
            self._entries.move_to_end(key)
            return value

    # -- writes -------------------------------------------------------------
    def put(self, path: str, info: FileInfo,
            stamp: Optional[int] = None) -> None:
        self._put(path, info, stamp)

    def put_listing(self, path: str, infos: List[FileInfo],
                    stamp: Optional[int] = None) -> None:
        self._put(path + self._LIST, infos, stamp)

    def _put(self, key: str, value, stamp: Optional[int]) -> None:
        with self._lock:
            if stamp is not None and self.applied_version is not None \
                    and stamp < self.applied_version:
                # the response predates invalidations already applied
                # here — caching it could retain a forever-stale entry
                return
            if key not in self._entries and \
                    len(self._entries) >= self._max:
                self._entries.popitem(last=False)
            self._entries[key] = (value, time.monotonic() + self._ttl, stamp)
            self._entries.move_to_end(key)

    # -- invalidation -------------------------------------------------------
    def invalidate(self, path: str) -> None:
        """Local write-through invalidation (this client's own mutation
        — effective immediately, before any push): drop the path, its
        parent's entry+listing, and every cached descendant."""
        with self._lock:
            self._invalidate_locked(path)

    def _invalidate_locked(self, path: str) -> None:
        self._entries.pop(path, None)
        self._entries.pop(path + self._LIST, None)
        prefix = path.rstrip("/") + "/"
        for p in [p for p in self._entries if p.startswith(prefix)]:
            self._entries.pop(p, None)
        parent = AlluxioURI(path).parent()
        if parent is not None:
            self._entries.pop(parent.path, None)
            self._entries.pop(parent.path + self._LIST, None)

    def apply_push(self, inv: dict) -> int:
        """Apply a master invalidation batch
        (``{"to": v, "prefixes": [...], "reset": bool}``) from the
        metrics-heartbeat response; returns the number of prefixes
        applied.  ``reset`` (first contact, or this client fell off the
        master's bounded ring) drops everything."""
        prefixes = inv.get("prefixes") or ()
        with self._lock:
            if inv.get("reset"):
                self._entries.clear()
            else:
                for p in prefixes:
                    self._invalidate_locked(p)
            to = inv.get("to")
            if to is not None:
                self.applied_version = int(to)
        return len(prefixes)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def size(self) -> int:
        with self._lock:
            return len(self._entries)


class FileSystem:
    """The user-facing client (reference: ``FileSystem.Factory.create``)."""

    def __init__(self, master_address: str,
                 conf: Optional[Configuration] = None) -> None:
        self._conf = conf or Configuration()
        if self._conf.get_bool(Keys.TRACE_ENABLED):
            from alluxio_tpu.utils.tracing import set_tracing_enabled

            set_tracing_enabled(True)
        from alluxio_tpu.utils.tracing import apply_trace_conf

        apply_trace_conf(self._conf)
        from alluxio_tpu.utils.profiler import apply_profile_conf

        apply_profile_conf(self._conf)
        from alluxio_tpu.security.authentication import client_metadata

        md = tuple(client_metadata(self._conf))
        fp_dir = self._conf.get(Keys.MASTER_FASTPATH_DIR)
        # HA: when the caller-supplied address names a member of the
        # conf master list (atpu.master.rpc.addresses), widen to the
        # whole list so every client path — metadata, block, and the
        # metrics heartbeat — rides leader redirects and rotation
        # across the quorum (docs/ha.md).  An explicit address OUTSIDE
        # the list wins untouched: attaching to a specific master (or
        # another cluster) must not be silently rerouted by site conf.
        conf_list = [a.strip() for a in
                     str(self._conf.get(Keys.MASTER_RPC_ADDRESSES)
                         or "").split(",") if a.strip()]
        given = [a.strip() for a in str(master_address).split(",")
                 if a.strip()]
        if conf_list and (not given or set(given) <= set(conf_list)):
            addresses = ",".join(conf_list)
        else:
            addresses = str(master_address)
        # retry budget from conf (atpu.user.rpc.retry.duration):
        # overload drills shorten it so a flooded client gives up fast
        # instead of stacking 30s of backoff behind a shedding master
        retry_kw = dict(
            retry_duration_s=self._conf.get_duration_s(
                Keys.USER_RPC_RETRY_MAX_DURATION),
            base_sleep_s=self._conf.get_duration_s(
                Keys.USER_RPC_RETRY_BASE_SLEEP),
            max_sleep_s=self._conf.get_duration_s(
                Keys.USER_RPC_RETRY_MAX_SLEEP))
        self.fs_master = FsMasterClient(
            addresses, metadata=md, fastpath_dir=fp_dir,
            standby_reads=self._conf.get_bool(
                Keys.USER_STANDBY_READS_ENABLED), **retry_kw)
        self.block_master = BlockMasterClient(addresses, metadata=md,
                                              fastpath_dir=fp_dir,
                                              **retry_kw)
        self.meta_master = MetaMasterClient(addresses, metadata=md,
                                            fastpath_dir=fp_dir,
                                            **retry_kw)
        identity = TieredIdentity.from_spec(
            self._conf.get(Keys.TIERED_IDENTITY),
            hostname=socket.gethostname())
        self.store = BlockStoreClient(
            self.block_master, identity=identity,
            read_policy=BlockLocationPolicy.create(
                self._conf.get(Keys.USER_BLOCK_READ_POLICY),
                identity=identity),
            write_policy=BlockLocationPolicy.create(
                self._conf.get(Keys.USER_BLOCK_WRITE_POLICY),
                identity=identity),
            short_circuit=self._conf.get_bool(Keys.USER_SHORT_CIRCUIT_ENABLED),
            passive_cache=self._conf.get_bool(
                Keys.USER_FILE_PASSIVE_CACHE_ENABLED),
            write_unavailable_window_s=self._conf.get_duration_s(
                Keys.USER_BLOCK_WRITE_UNAVAILABLE_WINDOW),
            streaming_chunk_size=self._conf.get_bytes(
                Keys.USER_STREAMING_READER_CHUNK_SIZE),
            streaming_writer_chunk_size=self._conf.get_bytes(
                Keys.USER_STREAMING_WRITER_CHUNK_SIZE),
            remote_read=RemoteReadConf.from_conf(self._conf),
            shm_enabled=self._conf.get_bool(Keys.USER_SHM_ENABLED),
            shm_cache_max=self._conf.get_int(
                Keys.USER_SHM_SEGMENT_CACHE_MAX),
            shm_renew_fraction=self._conf.get_float(
                Keys.USER_SHM_LEASE_RENEW_FRACTION),
            batch_read=BatchReadConf.from_conf(self._conf),
            native_fastpath=self._conf.get_bool(
                Keys.USER_NATIVE_FASTPATH_ENABLED))
        # pull cluster defaults once at start (reference: clients load
        # cluster-default config via the meta master on first connect)
        self._path_conf: Dict[str, Dict[str, str]] = {}
        self._path_conf_hash: Optional[str] = None
        self._config_hash: Optional[str] = None
        if self._conf.get_bool(Keys.USER_CONF_CLUSTER_DEFAULT_ENABLED):
            try:
                from alluxio_tpu.conf import Source

                # short retry: an offline master must not stall client
                # construction for the full 30s default retry window
                quick = MetaMasterClient(addresses, metadata=md,
                                         retry_duration_s=1.0)
                resp = quick.get_configuration()
                self._conf.merge(resp["properties"], Source.CLUSTER_DEFAULT)
                self._config_hash = resp["hash"]
                self._refresh_path_conf()
            except Exception:  # noqa: BLE001 - offline client still works
                pass
        md_cache_size = self._conf.get_int(Keys.USER_METADATA_CACHE_MAX_SIZE)
        self._md_cache = _MetadataCache(
            md_cache_size,
            self._conf.get_duration_s(Keys.USER_METADATA_CACHE_EXPIRATION_TIME)
        ) if md_cache_size > 0 and self._conf.get_bool(
            Keys.USER_METADATA_CACHE_ENABLED) else None
        from alluxio_tpu.metrics import metrics as _m

        self._md_hits = _m().counter("Client.MetadataCacheHits")
        self._md_misses = _m().counter("Client.MetadataCacheMisses")
        self._md_inval = _m().counter("Client.MetadataCacheInvalidated")
        self._sync_interval_ms = int(1000 * self._conf.get_duration_s(
            Keys.USER_FILE_METADATA_SYNC_INTERVAL))
        self._page_cache = None
        if self._conf.get_bool(Keys.USER_CLIENT_CACHE_ENABLED):
            from alluxio_tpu.client.cache.manager import LocalCacheManager

            self._page_cache = LocalCacheManager.from_conf(self._conf)
        #: config-hash handshake pacing (reference: ConfigHashSync): the
        #: metrics heartbeat re-checks the cluster-default hash at most
        #: once per atpu.user.conf.sync.interval — set BEFORE the
        #: heartbeat thread starts, which may tick immediately
        self._conf_sync_interval_s = self._conf.get_duration_s(
            Keys.USER_CONF_SYNC_INTERVAL)
        self._last_conf_sync = time.monotonic()
        self._metrics_thread = None
        if self._conf.get_bool(Keys.USER_METRICS_COLLECTION_ENABLED):
            from alluxio_tpu.heartbeat import (
                HeartbeatContext, HeartbeatThread,
            )

            self._metrics_thread = HeartbeatThread(
                HeartbeatContext.CLIENT_METRICS_HEARTBEAT,
                _ClientMetricsSync(self), self._conf.get_duration_s(
                    Keys.USER_METRICS_HEARTBEAT_INTERVAL))
            self._metrics_thread.start()

    def send_metrics(self) -> None:
        """Ship this client's metric snapshot — plus completed trace
        spans drained from the local ring — to the master for cluster
        aggregation and trace stitching (reference:
        ``client/metrics/ClientMasterSync``).  The response may carry a
        remediation tuning overlay; applying it here means pushed
        retunes land within one heartbeat interval, no extra RPC."""
        from alluxio_tpu.metrics import metrics
        from alluxio_tpu.utils.tracing import tracer

        spans = tracer().drain(500) if tracer().enabled else []
        from alluxio_tpu.utils.profiler import profiler

        flame = profiler().drain() if profiler().running else None
        resp = self.meta_master.metrics_heartbeat(
            f"client-{socket.gethostname()}-{id(self):x}",
            metrics().snapshot(), spans=spans, profile=flame,
            md_cache_version=self._md_cache.applied_version
            if self._md_cache is not None else None,
            want_md_invalidations=self._md_cache is not None)
        if self._md_cache is not None and isinstance(resp, dict) and \
                isinstance(resp.get("md_invalidations"), dict):
            self._md_inval.inc(
                self._md_cache.apply_push(resp["md_invalidations"]))
        if self._conf_sync_interval_s > 0 and \
                self._conf.get_bool(Keys.USER_CONF_CLUSTER_DEFAULT_ENABLED):
            now = time.monotonic()
            if now - self._last_conf_sync >= self._conf_sync_interval_s:
                self._last_conf_sync = now
                best_effort("config-hash sync", self.check_config_sync)
        if isinstance(resp, dict) and "conf_overlay_version" in resp:
            self.apply_conf_overlay(resp.get("conf_overlay") or {},
                                    int(resp["conf_overlay_version"]))

    #: master-pushable keys -> (clamp, apply) — everything else in an
    #: overlay is ignored: the push surface is a closed catalog, not a
    #: remote-write of arbitrary client conf
    _OVERLAY_CLAMPS = {
        "atpu.user.remote.read.hedge.quantile":
            lambda v: min(1.0, max(0.5, float(v))),
        "atpu.user.remote.read.concurrency":
            lambda v: min(64, max(1, int(float(v)))),
        "atpu.prefetch.budget.bytes":
            lambda v: min(4 << 30, max(16 << 20, int(float(v)))),
    }

    def apply_conf_overlay(self, overlay: Dict[str, object],
                           version: int) -> None:
        """Apply (or revert) the master's remediation tuning overlay.
        Idempotent per version; values are clamped client-side (a
        misbehaving master cannot push a client off a cliff); keys the
        overlay no longer carries revert to the value this client
        booted with."""
        if version == getattr(self, "_overlay_version", None):
            return
        self._overlay_version = version
        runtime = self.store.remote_read
        bases = getattr(self, "_overlay_bases", None)
        if bases is None:
            bases = self._overlay_bases = {
                "atpu.user.remote.read.hedge.quantile":
                    runtime.conf.hedge_quantile,
                "atpu.user.remote.read.concurrency":
                    runtime.conf.concurrency,
                "atpu.prefetch.budget.bytes": None,  # scheduler-owned
            }
        import dataclasses as _dc

        from alluxio_tpu.metrics import metrics

        applied = []
        replace = {}
        for key, clamp in self._OVERLAY_CLAMPS.items():
            raw = overlay.get(key)
            try:
                value = clamp(raw) if raw is not None else bases[key]
            except (TypeError, ValueError):
                continue  # a malformed push must not break heartbeats
            if key == "atpu.user.remote.read.hedge.quantile":
                replace["hedge_quantile"] = float(value)
            elif key == "atpu.user.remote.read.concurrency":
                replace["concurrency"] = int(value)
            elif key == "atpu.prefetch.budget.bytes":
                from alluxio_tpu.prefetch.scheduler import retune_budget

                # None = overlay withdrawn: restore each scheduler's
                # own configured budget
                retune_budget(None if raw is None else int(value))
            if raw is not None:
                applied.append(key)
        # the conf dataclass is frozen; swap it atomically so a stream
        # mid-read never sees a half-applied retune
        runtime.conf = _dc.replace(runtime.conf, **replace)
        metrics().counter("Client.ConfOverlayApplied").inc()
        self._overlay_active = applied

    @property
    def conf(self):
        """This client's resolved :class:`Configuration` (read-only use;
        layered services — e.g. the table reader — key their behavior
        off client conf without reaching into privates)."""
        return self._conf

    # ------------------------------------------------------------- metadata
    def get_status(self, path: "str | AlluxioURI") -> FileInfo:
        p = AlluxioURI(path).path
        if self._md_cache is None:
            return self.fs_master.get_status(
                p, sync_interval_ms=self._sync_interval_ms)
        hit = self._md_cache.get(p)
        if hit is not None:
            self._md_hits.inc()
            return hit
        self._md_misses.inc()
        info, stamp = self.fs_master.get_status(
            p, sync_interval_ms=self._sync_interval_ms, want_version=True)
        self._md_cache.put(p, info, stamp)
        return info

    def exists(self, path: "str | AlluxioURI") -> bool:
        return self.fs_master.exists(AlluxioURI(path).path)

    def list_status(self, path: "str | AlluxioURI",
                    recursive: bool = False) -> List[FileInfo]:
        p = AlluxioURI(path).path
        if self._md_cache is None or recursive:
            return self.fs_master.list_status(
                p, recursive=recursive,
                sync_interval_ms=self._sync_interval_ms)
        hit = self._md_cache.get_listing(p)
        if hit is not None:
            self._md_hits.inc()
            return list(hit)
        self._md_misses.inc()
        infos, stamp = self.fs_master.list_status(
            p, recursive=False, sync_interval_ms=self._sync_interval_ms,
            want_version=True)
        self._md_cache.put_listing(p, infos, stamp)
        return list(infos)

    def create_directory(self, path: "str | AlluxioURI", **opts) -> FileInfo:
        self._invalidate(path)
        return self.fs_master.create_directory(AlluxioURI(path).path, **opts)

    def delete(self, path: "str | AlluxioURI", recursive: bool = False,
               alluxio_only: bool = False) -> None:
        self._invalidate(path)
        self.fs_master.delete(AlluxioURI(path).path, recursive=recursive,
                              alluxio_only=alluxio_only)

    def rename(self, src: "str | AlluxioURI", dst: "str | AlluxioURI") -> None:
        self._invalidate(src)
        self._invalidate(dst)
        self.fs_master.rename(AlluxioURI(src).path, AlluxioURI(dst).path)

    def mount(self, path: "str | AlluxioURI", ufs_uri: str, **opts) -> None:
        self._invalidate(path)
        self.fs_master.mount(AlluxioURI(path).path, ufs_uri, **opts)

    def unmount(self, path: "str | AlluxioURI") -> None:
        self._invalidate(path)
        self.fs_master.unmount(AlluxioURI(path).path)

    def get_mount_points(self) -> List[MountPointInfo]:
        return self.fs_master.get_mount_points()

    def set_attribute(self, path: "str | AlluxioURI", **opts) -> None:
        self._invalidate(path)
        self.fs_master.set_attribute(AlluxioURI(path).path, **opts)

    def free(self, path: "str | AlluxioURI", recursive: bool = False,
             forced: bool = False) -> List[int]:
        return self.fs_master.free(AlluxioURI(path).path,
                                   recursive=recursive, forced=forced)

    def persist(self, path: "str | AlluxioURI") -> None:
        self.fs_master.schedule_async_persistence(AlluxioURI(path).path)

    def persist_now(self, path: "str | AlluxioURI", *,
                    expected_id: int = 0) -> str:
        """Synchronously write a cached file back to its UFS via a worker
        holding its blocks, then mark the inode persisted (reference: the
        worker-side persist executor driven by ``PersistDefinition``).

        ``expected_id`` pins the operation to one inode: a rename that
        put a DIFFERENT (already-persisted) file at ``path`` must fail
        the job — reporting success would silently drop the renamed
        file's ASYNC_THROUGH durability; the scheduler re-resolves the
        id and retries at the new path."""
        from alluxio_tpu.utils.exceptions import (
            FileDoesNotExistError, UnavailableError,
        )

        info = self.get_status(path)
        if expected_id and info.file_id != expected_id:
            raise FileDoesNotExistError(
                f"inode {expected_id} is no longer at {path} (found "
                f"{info.file_id}) — re-resolve and retry")
        if not info.ufs_path:
            raise UnavailableError(f"{path} has no UFS path to persist to")
        if info.persisted:
            return ""
        fbis = self.fs_master.get_file_block_info_list(info.path)
        # the persisting worker must hold every block locally: pick one
        # present in all blocks' location sets (LOCAL_FIRST writes keep a
        # file's blocks on one worker, so this is the common case)
        target = None
        if fbis:
            candidates = None
            addr_by_key = {}
            for fbi in fbis:
                keys = set()
                for loc in fbi.block_info.locations:
                    keys.add(loc.address.key())
                    addr_by_key[loc.address.key()] = loc.address
                candidates = keys if candidates is None else \
                    (candidates & keys)
            if not candidates:
                raise UnavailableError(
                    f"no single worker holds all cached blocks of {path}")
            target = addr_by_key[sorted(candidates)[0]]
        if target is None:
            # zero-block file: master creates the empty UFS object, then
            # marks persisted (a PERSISTED inode with no UFS object would
            # be deleted by the next metadata sync)
            fingerprint = self.fs_master.commit_persist(
                info.path, "", expected_id=info.file_id)
            self._invalidate(path)
            return fingerprint
        # persist to a TEMP UFS path; the master promotes it
        # (commit_persist) only while the SAME inode is still live, so a
        # concurrent delete or delete+recreate can never leave a zombie
        # or stale UFS file for metadata sync to resurrect
        # (reference: temp persist paths + UfsCleaner for abandoned ones)
        import uuid

        d, _, name = info.ufs_path.rpartition("/")
        temp_ufs = f"{d}/.atpu_persist.{name}.{uuid.uuid4().hex[:8]}"
        worker = self.store.worker_client(target)
        worker.persist_file(
            temp_ufs, [fbi.block_info.block_id for fbi in fbis],
            info.mount_id)
        fingerprint = self.fs_master.commit_persist(
            info.path, temp_ufs, expected_id=info.file_id)
        self._invalidate(path)
        return fingerprint

    def _invalidate(self, path) -> None:
        if self._md_cache is not None:
            self._md_cache.invalidate(AlluxioURI(path).path)

    # ----------------------------------------------------------------- data
    def open_file(self, path: "str | AlluxioURI", *,
                  cache: Optional[bool] = None,
                  info: Optional[FileInfo] = None,
                  max_open_streams: Optional[int] = None) -> FileInStream:
        """``info``: a FileInfo the caller already holds (skips the
        get_status round-trip — the loader's first-batch path).
        ``max_open_streams``: cap on cached per-block streams (worker
        pins) — long-lived many-file holders pass 1."""
        if info is None:
            info = self.get_status(path)
        if info.folder:
            from alluxio_tpu.utils.exceptions import InvalidArgumentError

            raise InvalidArgumentError(f"{path} is a directory")
        if cache is None:
            cache = self._conf.get(Keys.USER_FILE_READ_TYPE_DEFAULT) != \
                "NO_CACHE"
        stream = FileInStream(self.fs_master, self.store, info,
                              cache=cache,
                              max_open_streams=max_open_streams)
        if self._page_cache is not None:
            from alluxio_tpu.client.cache.stream import CachingFileInStream

            return CachingFileInStream(stream, self._page_cache)
        return stream

    def _refresh_path_conf(self) -> None:
        resp = self.meta_master.get_path_conf()
        self._path_conf = resp.get("properties", {})
        self._path_conf_hash = resp.get("hash")

    def path_default(self, path: "str | AlluxioURI",
                     key) -> Optional[str]:
        """Per-path cluster default for a property, longest prefix wins
        (reference: PathProperties served by the meta master)."""
        if not self._path_conf:
            return None
        from alluxio_tpu.master.path_properties import resolve_path_property

        name = key if isinstance(key, str) else key.name
        return resolve_path_property(self._path_conf,
                                     AlluxioURI(path).path, name)

    def create_file(self, path: "str | AlluxioURI", *,
                    write_type: Optional[str] = None,
                    block_size_bytes: Optional[int] = None,
                    tier: str = "", pinned: bool = False,
                    **opts) -> FileOutStream:
        self._invalidate(path)
        wt = write_type or \
            self.path_default(path, Keys.USER_FILE_WRITE_TYPE_DEFAULT) or \
            self._conf.get(Keys.USER_FILE_WRITE_TYPE_DEFAULT)
        if "replication_min" not in opts:
            rep = self.path_default(path, Keys.USER_FILE_REPLICATION_MIN)
            if rep is not None:
                opts["replication_min"] = int(rep)
        if "replication_max" not in opts:
            rep = self.path_default(path, Keys.USER_FILE_REPLICATION_MAX)
            if rep is None:
                rep = self._conf.get_int(Keys.USER_FILE_REPLICATION_MAX)
            if rep is not None and int(rep) >= 0:
                opts["replication_max"] = int(rep)
        persist_on_complete = wt == WriteType.ASYNC_THROUGH
        info = self.fs_master.create_file(
            AlluxioURI(path).path, block_size_bytes=block_size_bytes,
            persist_on_complete=persist_on_complete, **opts)
        return FileOutStream(self.fs_master, self.store, info,
                             write_type=wt, tier=tier, pinned=pinned)

    def read_all(self, path: "str | AlluxioURI") -> bytes:
        with self.open_file(path) as f:
            return f.read()

    def write_all(self, path: "str | AlluxioURI", data: bytes,
                  **opts) -> None:
        with self.create_file(path, **opts) as f:
            f.write(data)

    # -------------------------------------------------- live reconfiguration
    def check_config_sync(self) -> bool:
        """Config-hash handshake: pull cluster defaults when the master's
        hash moves (reference: ``ConfigHashSync.java:36``). Returns True if
        config was re-synced."""
        h = self.meta_master.get_config_hash()
        if self._config_hash is None:
            self._config_hash = h
            return False
        if h != self._config_hash:
            from alluxio_tpu.conf import Source

            resp = self.meta_master.get_configuration()
            self._conf.merge(resp["properties"], Source.CLUSTER_DEFAULT)
            self._config_hash = resp["hash"]
            try:
                self._refresh_path_conf()
            except Exception:  # noqa: BLE001 - older master without the RPC
                pass
            return True
        return False

    # -------------------------------------------------------------- cleanup
    def close(self) -> None:
        if self._metrics_thread is not None:
            self._metrics_thread.stop()
            self._metrics_thread = None
        self.store.close()
        if self._page_cache is not None:
            self._page_cache.close()


class _ClientMetricsSync:
    """Heartbeat executor shipping client metrics (reference:
    ``client/metrics/ClientMasterSync.java``)."""

    def __init__(self, fs: FileSystem) -> None:
        self._fs = fs

    def heartbeat(self) -> None:
        try:
            self._fs.send_metrics()
        except Exception:  # noqa: BLE001 master transition: retry next tick
            pass

    def close(self) -> None:
        pass
