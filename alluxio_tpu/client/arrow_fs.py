"""pyarrow FileSystem adapter: the namespace as a first-class Arrow FS.

Re-design of the reference's HDFS-compatible client
(``core/client/hdfs/src/main/java/alluxio/hadoop/AbstractFileSystem.java:80``
— the Hadoop ``FileSystem`` SPI that lets Spark/Hive/Presto address
``alluxio://`` paths) for the Python data stack: an
``pyarrow.fs.FileSystemHandler`` over the native client, so
``pyarrow.dataset`` / ``pyarrow.parquet`` / pandas / Dask address
``atpu`` paths with true random-access reads (positioned ``pread``
against cached blocks, not a buffered byte stream).

Usage::

    fs = arrow_file_system("localhost:19998")
    pq.write_table(table, "warehouse/t.parquet", filesystem=fs)
    ds.dataset("warehouse", filesystem=fs).to_table()
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import List, Optional

from alluxio_tpu.utils.exceptions import (
    FileAlreadyExistsError, FileDoesNotExistError,
)


def _require_pyarrow():
    try:
        import pyarrow.fs as pafs
    except ImportError as e:  # pragma: no cover - baked into the image
        raise RuntimeError("pyarrow is required for the Arrow FS "
                           "adapter") from e
    return pafs


def _norm(path: str) -> str:
    from alluxio_tpu.utils.uri import AlluxioURI

    # AlluxioURI strips scheme+authority and normalizes ('..', '//')
    return AlluxioURI(path.strip()).path


class _InputFile:
    """Random-access reader pyarrow wraps via ``PythonFile``: ``read``
    serves from the positioned ``pread`` path so parquet footer/column
    seeks hit cached blocks directly."""

    def __init__(self, stream, length: int) -> None:
        self._s = stream
        self._len = length
        self._pos = 0
        self.closed = False

    def size(self) -> int:
        return self._len

    def readable(self) -> bool:
        return True

    def writable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return True

    def tell(self) -> int:
        return self._pos

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 1:
            offset += self._pos
        elif whence == 2:
            offset += self._len
        self._pos = max(0, min(offset, self._len))
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._len - self._pos
        data = self._s.pread(self._pos, n)
        self._pos += len(data)
        return data

    def flush(self) -> None:
        pass

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._s.close()


class _OutputFile:
    """Sequential writer over ``FileOutStream``."""

    def __init__(self, stream) -> None:
        self._s = stream
        self._pos = 0
        self.closed = False

    def writable(self) -> bool:
        return True

    def readable(self) -> bool:
        return False

    def seekable(self) -> bool:
        return False

    def tell(self) -> int:
        return self._pos

    def write(self, data) -> int:
        data = bytes(data)
        self._s.write(data)
        self._pos += len(data)
        return len(data)

    def flush(self) -> None:
        pass

    def close(self) -> None:
        if not self.closed:
            self.closed = True
            self._s.close()


def _handler_class():
    """Build the handler class lazily (subclassing
    ``pyarrow.fs.FileSystemHandler`` imports pyarrow)."""
    pafs = _require_pyarrow()

    class AlluxioTpuArrowHandler(pafs.FileSystemHandler):
        """``FileSystemHandler`` over the native ``FileSystem`` client."""

        def __init__(self, fs) -> None:
            self._fs = fs

        # -- identity --------------------------------------------------------
        def get_type_name(self) -> str:
            return "atpu"

        def normalize_path(self, path: str) -> str:
            return _norm(path)

        def __eq__(self, other) -> bool:
            return isinstance(other, AlluxioTpuArrowHandler) and \
                other._fs is self._fs

        def __ne__(self, other) -> bool:
            return not self.__eq__(other)

        # -- info ------------------------------------------------------------
        def _info(self, path: str):
            from pyarrow.fs import FileInfo, FileType

            path = _norm(path)
            try:
                st = self._fs.get_status(path)
            except FileDoesNotExistError:
                return FileInfo(path, FileType.NotFound)
            mtime = datetime.fromtimestamp(
                st.last_modification_time_ms / 1000.0, tz=timezone.utc)
            if st.folder:
                return FileInfo(path, FileType.Directory, mtime=mtime)
            return FileInfo(path, FileType.File, size=st.length,
                            mtime=mtime)

        def get_file_info(self, paths: List[str]):
            return [self._info(p) for p in paths]

        def get_file_info_selector(self, selector):
            from pyarrow.fs import FileInfo, FileType

            base = _norm(selector.base_dir)
            try:
                infos = self._fs.list_status(
                    base, recursive=selector.recursive)
            except FileDoesNotExistError:
                if selector.allow_not_found:
                    return []
                raise FileNotFoundError(base)
            out = []
            for st in infos:
                mtime = datetime.fromtimestamp(
                    st.last_modification_time_ms / 1000.0,
                    tz=timezone.utc)
                if st.folder:
                    out.append(FileInfo(st.path, FileType.Directory,
                                        mtime=mtime))
                else:
                    out.append(FileInfo(st.path, FileType.File,
                                        size=st.length, mtime=mtime))
            return out

        # -- directories -----------------------------------------------------
        def create_dir(self, path: str, recursive: bool) -> None:
            try:
                self._fs.create_directory(_norm(path), recursive=recursive,
                                          allow_exists=True)
            except FileAlreadyExistsError:
                pass

        def delete_dir(self, path: str) -> None:
            self._fs.delete(_norm(path), recursive=True)

        def delete_dir_contents(self, path: str,
                                missing_dir_ok: bool = False) -> None:
            path = _norm(path)
            if path == "/":
                raise ValueError(
                    "delete_dir_contents('/') is forbidden; use "
                    "delete_root_dir_contents")
            try:
                children = self._fs.list_status(path)
            except FileDoesNotExistError:
                if missing_dir_ok:
                    return
                raise FileNotFoundError(path)
            for st in children:
                self._fs.delete(st.path, recursive=True)

        def delete_root_dir_contents(self) -> None:
            for st in self._fs.list_status("/"):
                self._fs.delete(st.path, recursive=True)

        # -- files -----------------------------------------------------------
        def delete_file(self, path: str) -> None:
            path = _norm(path)
            try:
                st = self._fs.get_status(path)
            except FileDoesNotExistError:
                raise FileNotFoundError(path)
            if st.folder:
                raise IsADirectoryError(path)
            self._fs.delete(path)

        def move(self, src: str, dest: str) -> None:
            self._fs.rename(_norm(src), _norm(dest))

        def copy_file(self, src: str, dest: str) -> None:
            with self._fs.open_file(_norm(src)) as fin:
                out = self._fs.create_file(_norm(dest), overwrite=True)
                with out:
                    pos = 0
                    while True:
                        chunk = fin.pread(pos, 4 << 20)
                        if not chunk:
                            break
                        out.write(chunk)
                        pos += len(chunk)

        # -- streams ---------------------------------------------------------
        def open_input_stream(self, path: str):
            import pyarrow as pa

            return pa.PythonFile(self._open_reader(path), mode="r")

        def open_input_file(self, path: str):
            import pyarrow as pa

            return pa.PythonFile(self._open_reader(path), mode="r")

        def _open_reader(self, path: str) -> _InputFile:
            path = _norm(path)
            try:
                st = self._fs.get_status(path)
            except FileDoesNotExistError:
                raise FileNotFoundError(path)
            if st.folder:
                raise IsADirectoryError(path)
            return _InputFile(self._fs.open_file(path, info=st), st.length)

        def open_output_stream(self, path: str, metadata=None):
            import pyarrow as pa

            out = self._fs.create_file(_norm(path), overwrite=True)
            return pa.PythonFile(_OutputFile(out), mode="w")

        def open_append_stream(self, path: str, metadata=None):
            raise NotImplementedError(
                "append is not supported (blocks are immutable once "
                "committed; rewrite the file instead)")

    return AlluxioTpuArrowHandler


def arrow_file_system(master: Optional[str] = None, *, fs=None, conf=None):
    """An ``pyarrow.fs.PyFileSystem`` over the namespace.

    Pass either a live client ``fs`` or a ``master`` address (plus
    optional ``conf``) to own one.
    """
    pafs = _require_pyarrow()
    if fs is None:
        from alluxio_tpu.client.file_system import FileSystem

        fs = FileSystem(master, conf=conf)
    handler = _handler_class()(fs)
    return pafs.PyFileSystem(handler)
