"""File-level streams over per-block streams.

Re-design of ``core/client/fs/src/main/java/alluxio/client/file/
{AlluxioFileInStream.java:66,AlluxioFileOutStream.java:56}``: a seekable
read stream that walks block streams (with failed-worker retry), and a
write stream that allocates a new block id per block boundary and completes
the file on close. Write types mirror the reference
(``MUST_CACHE``/``ASYNC_THROUGH``/``CACHE_THROUGH``/``THROUGH``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

from alluxio_tpu.client.block_store import BlockStoreClient
from alluxio_tpu.client.block_streams import BlockInStream, BlockOutStream
from alluxio_tpu.metrics import metrics
from alluxio_tpu.rpc.clients import FsMasterClient
from alluxio_tpu.utils.exceptions import (
    BlockDoesNotExistError, InvalidArgumentError, UnavailableError,
)
from alluxio_tpu.utils.wire import FileBlockInfo, FileInfo


class WriteType:
    MUST_CACHE = "MUST_CACHE"
    CACHE_THROUGH = "CACHE_THROUGH"
    THROUGH = "THROUGH"
    ASYNC_THROUGH = "ASYNC_THROUGH"
    NONE = "NONE"


class ReadType:
    NO_CACHE = "NO_CACHE"
    CACHE = "CACHE"
    CACHE_PROMOTE = "CACHE_PROMOTE"


class FileInStream:
    """Seekable whole-file reader (reference: AlluxioFileInStream)."""

    #: cap on cached open per-block streams. Each open short-circuit
    #: stream holds a worker-side PIN (eviction can't unlink a mapped
    #: block), so the cap bounds unevictable blocks per stream:
    #: ``max_open_streams * open_streams_per_worker``. Workloads holding
    #: many long-lived FileInStreams (the JAX loader) pass 1.
    MAX_OPEN_STREAMS = 4

    def __init__(self, fs_master: FsMasterClient, store: BlockStoreClient,
                 info: FileInfo, *, cache: bool = True,
                 max_open_streams: Optional[int] = None) -> None:
        self._fs = fs_master
        self._store = store
        self.info = info
        self._cache = cache
        self._pos = 0
        self._block_infos: Optional[List[FileBlockInfo]] = None
        #: small LRU of OPEN per-block streams keyed by block index: a
        #: positioned-read workload hopping between blocks (random-4k
        #: over a multi-block file) must not pay a lease+mmap reopen on
        #: every block switch (reference keeps positioned-read streams
        #: cached per block the same way)
        self._streams: "dict[int, BlockInStream]" = {}
        self._max_open_streams = max_open_streams or self.MAX_OPEN_STREAMS

    # -- metadata ------------------------------------------------------------
    @property
    def length(self) -> int:
        return self.info.length

    def _blocks(self) -> List[FileBlockInfo]:
        if self._block_infos is None:
            self._block_infos = self._fs.get_file_block_info_list(
                self.info.path)
        return self._block_infos

    def _ufs_info_for(self, index: int) -> Optional[dict]:
        if not self.info.ufs_path or not self.info.persisted:
            return None
        bs = self.info.block_size_bytes
        fbi = self._blocks()[index]
        return {"ufs_path": self.info.ufs_path, "offset": index * bs,
                "length": fbi.block_info.length,
                "mount_id": self.info.mount_id}

    # -- stream protocol -----------------------------------------------------
    def seek(self, pos: int) -> None:
        if pos < 0 or pos > self.length:
            raise InvalidArgumentError(f"seek {pos} out of [0, {self.length}]")
        self._pos = pos

    def tell(self) -> int:
        return self._pos

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = self.length - self._pos
        self._pos, out = self._read_at(self._pos, n)
        return out

    def pread(self, offset: int, n: int) -> bytes:
        """Positioned read without moving the cursor
        (reference: positioned read, ``block_worker.proto:68``)."""
        return self._read_at(offset, n)[1]

    def _read_at(self, pos: int, n: int) -> "tuple[int, bytes]":
        # chunk list + single join: the block streams hand back
        # freshly-owned bytes (mmap slice / gRPC frame), a one-chunk
        # read returns them as-is, and a spanning read pays exactly one
        # assembly pass — the old bytearray.extend + bytes() pair cost
        # two extra full passes over the data
        chunks = []
        while n > 0 and pos < self.length:
            chunk = self._read_from_block(pos, n)
            if not chunk:
                break
            chunks.append(chunk)
            pos += len(chunk)
            n -= len(chunk)
        return pos, chunks[0] if len(chunks) == 1 else b"".join(chunks)

    _MAX_READ_ATTEMPTS = 3

    def _read_from_block(self, pos: int, n: int) -> bytes:
        bs = self.info.block_size_bytes
        index = pos // bs
        offset_in_block = pos % bs
        last_err: Optional[Exception] = None
        excluded: Set[str] = set()
        for attempt in range(self._MAX_READ_ATTEMPTS):
            if attempt:
                time.sleep(0.05 * attempt)
            try:
                stream = self._block_stream(index, exclude=excluded)
            except UnavailableError as e:
                # no source yet (commit may still be propagating to the
                # master): refresh locations and retry briefly
                last_err = e
                self._block_infos = None
                continue
            readable = stream.length - offset_in_block
            if readable <= 0:
                return b""
            try:
                t0 = time.perf_counter()
                chunk = stream.pread(offset_in_block, min(n, readable))
                # per-tier read latency: the block stream tags its
                # serving source AFTER the read (a worker may self-heal
                # a stale location into a UFS read-through mid-call)
                metrics().timer(
                    f"Client.BlockReadTime.{stream.source_bucket()}"
                ).update(time.perf_counter() - t0)
                return chunk
            except UnavailableError as e:
                # serving worker died mid-read: remember it, refresh the
                # block's locations, retry another replica / UFS fallback
                # (reference: AlluxioFileInStream failed-worker retry,
                # :94-95)
                last_err = e
                self._store.mark_failed(stream.address)
                # every cached stream to the dead worker is equally
                # doomed: drop them all, or blocks cached there would
                # each burn a failed attempt + backoff before failover
                dead = stream.address.key() if stream.address else None
                for i in [i for i, s2 in self._streams.items()
                          if s2.address is not None
                          and s2.address.key() == dead]:
                    self._drop_stream(i)
                self._drop_stream(index)
                self._block_infos = None
            except BlockDoesNotExistError as e:
                # stale location (evicted since the master's last heartbeat):
                # the worker is healthy, so don't mark it failed — exclude it
                # for this read only and retry another replica
                last_err = e
                if stream.address is not None:
                    excluded.add(stream.address.key())
                self._drop_stream(index)
                self._block_infos = None
        raise last_err  # type: ignore[misc]

    def _drop_stream(self, index: int) -> None:
        stream = self._streams.pop(index, None)
        if stream is not None:
            try:
                stream.close()
            except Exception:  # noqa: BLE001 - already broken
                pass

    def _block_stream(self, index: int,
                      exclude: Optional[Set[str]] = None) -> BlockInStream:
        cached = self._streams.get(index)
        if cached is not None:
            if not exclude or (cached.address is None or
                               cached.address.key() not in exclude):
                # LRU touch
                self._streams[index] = self._streams.pop(index)
                return cached
            self._drop_stream(index)
        while len(self._streams) >= self._max_open_streams:
            self._drop_stream(next(iter(self._streams)))
        fbi = self._blocks()[index]
        stream = self._store.open_block(
            fbi, ufs_info=self._ufs_info_for(index),
            cache_cold_reads=self._cache, exclude=exclude)
        self._streams[index] = stream
        return stream

    def block_stream(self, index: int) -> BlockInStream:
        """Expose the per-block stream — the zero-copy JAX path uses this to
        mmap whole blocks instead of byte-copy reads."""
        return self._block_stream(index)

    def pread_ranges(self, ranges: "List[tuple]", *,
                     route_stats: Optional[Dict[str, int]] = None
                     ) -> List[bytes]:
        """Scatter/gather positioned reads over a list of ``(offset,
        length)`` file ranges — the range-list entry point of the
        ``choose_route`` ladder (docs/table_reads.md). Ranges are split
        at block boundaries, grouped per block, and each block group is
        served by the best transport in ONE pass: same-host SHM blocks
        hand back zero-copy ``memoryview`` slices, wire-crossing groups
        ride ``pread_many`` (small ops coalesce into ``read_many``
        scatter batches through the native plan executor, large ops take
        the striped plane) — instead of one RPC per seek.

        Results come back in request order as buffer objects (``bytes``
        or ``memoryview``); a range past EOF truncates exactly like
        :meth:`pread`. Any block-group failure falls back to the per-op
        :meth:`pread` path, which carries the failed-worker retry
        ladder — the router can only make reads faster, never fail them.
        ``route_stats``: optional dict the served byte counts are added
        into, keyed by route (``shm``/``batch``/``striped``/``stream``).
        """
        from alluxio_tpu.client.remote_read import choose_route

        bs = self.info.block_size_bytes or self.length or 1
        # split ranges at block boundaries: (block, off_in_block, n,
        # range_index) preserving request order within each range
        by_block: "Dict[int, List[tuple]]" = {}
        parts_per_range: List[List[Optional[bytes]]] = []
        for r_i, (off, n) in enumerate(ranges):
            off = max(0, int(off))
            n = max(0, min(int(n), self.length - off))
            slots: List[Optional[bytes]] = []
            while n > 0:
                index = off // bs
                off_in_block = off % bs
                take = min(n, bs - off_in_block)
                by_block.setdefault(index, []).append(
                    (off_in_block, take, r_i, len(slots)))
                slots.append(None)
                off += take
                n -= take
            parts_per_range.append(slots)
        rt = self._store.remote_read
        striped_conf = rt.conf if rt is not None and rt.enabled else None
        batch_conf = getattr(self._store, "batch_read", None)
        for index in sorted(by_block):
            ops = by_block[index]
            try:
                stream = self._block_stream(index)
                if hasattr(stream, "pread_view"):
                    # same-host SHM segment: every op is a zero-copy view
                    for off_in_block, take, r_i, slot in ops:
                        view = stream.pread_view(off_in_block, take)
                        parts_per_range[r_i][slot] = view
                        self._note_route(route_stats, "shm", len(view))
                    continue
                outs = stream.pread_many([o[0] for o in ops],
                                         [o[1] for o in ops])
            except Exception:  # noqa: BLE001 - per-op ladder handles retry
                outs = [self.pread(index * bs + o[0], o[1]) for o in ops]
            for (off_in_block, take, r_i, slot), out in zip(ops, outs):
                parts_per_range[r_i][slot] = out
                self._note_route(
                    route_stats,
                    choose_route(take, batch=batch_conf,
                                 batch_ops=len(ops),
                                 striped=striped_conf), len(out))
        out: List[bytes] = []
        for slots in parts_per_range:
            if not slots:
                out.append(b"")
            elif len(slots) == 1:
                out.append(slots[0])
            else:
                out.append(b"".join(slots))
        return out

    @staticmethod
    def _note_route(route_stats: Optional[Dict[str, int]], route: str,
                    nbytes: int) -> None:
        if route_stats is not None:
            route_stats[route] = route_stats.get(route, 0) + nbytes

    def close(self) -> None:
        for index in list(self._streams):
            self._drop_stream(index)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class FileOutStream:
    """Whole-file writer (reference: AlluxioFileOutStream)."""

    def __init__(self, fs_master: FsMasterClient, store: BlockStoreClient,
                 info: FileInfo, *, write_type: str = WriteType.ASYNC_THROUGH,
                 tier: str = "", pinned: bool = False) -> None:
        self._fs = fs_master
        self._store = store
        self.info = info
        self._write_type = write_type
        self._tier = tier
        self._pinned = pinned
        self._block_size = info.block_size_bytes
        self._current: Optional[BlockOutStream] = None
        self._current_written = 0
        self._block_ids: List[int] = []
        self.written = 0
        self._closed = False
        #: sticky writer target: all blocks of one stream land on one worker
        self._worker_address = None

    def write(self, data: bytes) -> int:
        if self._closed:
            raise InvalidArgumentError("stream closed")
        view = memoryview(data)
        while len(view) > 0:
            if self._current is None:
                block_id = self._fs.get_new_block_id(self.info.path)
                self._current = self._store.open_block_writer(
                    block_id, size_hint=self._block_size,
                    tier=self._tier, pinned=self._pinned,
                    preferred=self._worker_address)
                self._worker_address = self._store.last_write_address
                self._block_ids.append(block_id)
                self._current_written = 0
            room = self._block_size - self._current_written
            chunk = view[:room]
            # writers take buffers: the local path hands the view to
            # BufferedWriter as-is, the gRPC path re-chunks and owns its
            # copies — a bytes() here would re-copy every written byte
            self._current.write(chunk)
            self._current_written += len(chunk)
            self.written += len(chunk)
            view = view[len(chunk):]
            if self._current_written >= self._block_size:
                self._current.close()
                self._current = None
        return len(data)

    def cancel(self) -> None:
        if self._current is not None:
            self._current.close(cancel=True)
            self._current = None
        self._closed = True
        self._fs.delete(self.info.path)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._current is not None:
            self._current.close()
            self._current = None
        self._fs.complete_file(self.info.path, length=self.written)
        if self._write_type == WriteType.ASYNC_THROUGH:
            self._fs.schedule_async_persistence(self.info.path)
        elif self._write_type in (WriteType.THROUGH, WriteType.CACHE_THROUGH):
            self._persist_sync()
            if self._write_type == WriteType.THROUGH:
                # THROUGH keeps no cached copy (reference semantics)
                self._fs.free(self.info.path, forced=True)

    def _persist_sync(self) -> None:
        """Synchronous persist via the worker holding the cached blocks
        (reference: CACHE_THROUGH's UfsFileWriteHandler path; here the
        worker-side persist executor writes the UFS file in one shot).
        Uses the same temp-path + master-commit protocol as async persist
        so a concurrent delete can never leave a zombie UFS file."""
        st = self._fs.get_status(self.info.path)
        if not st.ufs_path:
            return
        worker = self._store.last_write_worker
        if worker is None:
            return
        if not self._block_ids:  # zero-byte file
            self._fs.commit_persist(self.info.path, "",
                                    expected_id=st.file_id)
            return
        import uuid

        d, _, name = st.ufs_path.rpartition("/")
        temp_ufs = f"{d}/.atpu_persist.{name}.{uuid.uuid4().hex[:8]}"
        worker.persist_file(temp_ufs, self._block_ids, st.mount_id)
        self._fs.commit_persist(self.info.path, temp_ufs,
                                expected_id=st.file_id)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is not None:
            self.cancel()
        else:
            self.close()
        return False

