"""Per-block data streams: the transport decision ladder.

Re-design of ``core/client/fs/src/main/java/alluxio/client/block/stream/
{BlockInStream.java:97,LocalFileDataReader.java:41,GrpcDataReader.java:49,
LocalFileDataWriter,GrpcDataWriter}.java``:

Read ladder (closest wins):
1. **Short-circuit mmap** — block cached on a same-host worker: lease the
   file path (``open_local_block``) and mmap it. Zero RPC per byte, zero
   copy; the mmap'd buffer can be handed to ``jax.device_put`` directly.
2. **gRPC stream** — cached on a remote worker.
3. **UFS fallback through a worker** — not cached anywhere: a
   policy-chosen worker read-throughs from the UFS (caching it), client
   streams from that worker.

Write ladder mirrors it: short-circuit file write locally, gRPC stream
remotely.
"""

from __future__ import annotations

import mmap
import os
import queue
import socket
import threading
from concurrent import futures
from typing import Iterator, List, Optional

import numpy as np

from alluxio_tpu.rpc.clients import WorkerClient
from alluxio_tpu.utils.exceptions import UnavailableError
from alluxio_tpu.utils.wire import BlockInfo, WorkerNetAddress


def _record_read(bucket: str, nbytes: int) -> None:
    """Per-source read accounting: ``Client.BytesRead.<bucket>`` /
    ``Client.BlocksRead.<bucket>`` counters (additive — they roll up to
    ``Cluster.*`` on the metrics heartbeat)."""
    from alluxio_tpu.metrics import metrics

    m = metrics()
    m.counter(f"Client.BytesRead.{bucket}").inc(nbytes)
    m.counter(f"Client.BlocksRead.{bucket}").inc()


def is_local_worker(address: WorkerNetAddress, local_hostname: str) -> bool:
    """Same-host check gate for the short-circuit path: the worker's shm
    dir must be a real local directory."""
    if address.host not in (local_hostname, "localhost", "127.0.0.1",
                            socket.gethostname()):
        return False
    return bool(address.shm_dir) and os.path.isdir(address.shm_dir)


class BlockInStream:
    """Positioned reads over one block."""

    def __init__(self, block_id: int, length: int) -> None:
        self.block_id = block_id
        self.length = length
        #: serving worker (set by BlockStoreClient); failed-worker retry
        #: marks it when a read dies mid-stream
        self.address = None
        #: raw serving source of the LAST read: a worker tier alias
        #: ("MEM"/"SSD"/...), "SHM" for short-circuit, or "UFS"
        self.last_source: Optional[str] = None

    def pread(self, offset: int, n: int) -> bytes:
        raise NotImplementedError

    def read_all(self) -> bytes:
        return self.pread(0, self.length)

    def memoryview(self) -> Optional[memoryview]:
        """Zero-copy view when the source is local; None otherwise."""
        return None

    @property
    def source(self) -> str:
        raise NotImplementedError

    def source_bucket(self) -> str:
        """The last read's serving source, normalized to an input-doctor
        bucket: ``shm`` (same-host /dev/shm mmap), ``remote`` (cached on
        a remote worker, whatever its tier), ``ufs`` (cold
        read-through), or ``unknown``."""
        src = self.last_source
        if src is None:
            return "unknown"
        if src == "SHM":
            return "shm"
        if src == "UFS":
            return "ufs"
        return "remote"

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class LocalBlockInStream(BlockInStream):
    """Short-circuit: mmap the worker's block file via a path lease
    (reference: ``LocalFileDataReader.java:41``)."""

    source = "LOCAL"

    def __init__(self, worker: WorkerClient, session_id: int, block_id: int):
        lease = worker.open_local_block(session_id, block_id)
        super().__init__(block_id, lease["length"])
        self.last_source = "SHM"
        self._worker = worker
        self._session = session_id
        self._path = lease["path"]
        self._f = open(self._path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, prot=mmap.PROT_READ) \
            if lease["length"] > 0 else None

    def pread(self, offset: int, n: int) -> bytes:
        if self._mm is None:
            return b""
        out = self._mm[offset:offset + n]
        _record_read("shm", len(out))
        return out

    def memoryview(self) -> Optional[memoryview]:
        return memoryview(self._mm) if self._mm is not None else memoryview(b"")

    def numpy_view(self, dtype=np.uint8) -> np.ndarray:
        """Zero-copy ndarray over the mmap — feed straight to device_put."""
        if self._mm is None:
            return np.empty(0, dtype=dtype)
        _record_read("shm", len(self._mm))
        return np.frombuffer(self._mm, dtype=dtype)

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # a numpy view is still live (e.g. in-flight device_put);
                # leave the mapping to GC — on Linux the pages stay valid
                # even if the file is later unlinked by eviction
                pass
            self._mm = None
        self._f.close()
        try:
            self._worker.close_local_block(self._session, self.block_id)
        except Exception:  # noqa: BLE001 - lease expires with session anyway
            pass


class GrpcBlockInStream(BlockInStream):
    """Remote read over the gRPC chunk stream
    (reference: ``GrpcDataReader.java:49``)."""

    source = "REMOTE"

    def __init__(self, worker: WorkerClient, block_id: int, length: int,
                 *, ufs: Optional[dict] = None, cache: bool = True,
                 chunk_size: int = 1 << 20) -> None:
        super().__init__(block_id, length)
        self._worker = worker
        self._ufs = ufs
        self._cache = cache
        self._chunk = chunk_size

    def pread(self, offset: int, n: int) -> bytes:
        out = bytearray()
        source = None
        for msg in self._worker.read_block(
                self.block_id, offset=offset, length=n,
                chunk_size=self._chunk, ufs=self._ufs, cache=self._cache):
            out.extend(msg["data"])
            source = msg.get("source", source)
        # a pre-source-tagging worker sends no field: the read still
        # went to a remote worker's cache (cold reads raise without a
        # UFS descriptor, and with one the worker tags "UFS")
        self.last_source = source or "REMOTE"
        _record_read(self.source_bucket(), len(out))
        return bytes(out)

    @property
    def is_ufs_fallback(self) -> bool:
        return self._ufs is not None


class BlockOutStream:
    def __init__(self, block_id: int) -> None:
        self.block_id = block_id
        self.written = 0

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def close(self, cancel: bool = False) -> None:
        raise NotImplementedError

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        self.close(cancel=exc_type is not None)
        return False


class LocalBlockOutStream(BlockOutStream):
    """Short-circuit write: append straight to the worker's temp file
    (reference: ``LocalFileDataWriter`` + ``CreateLocalBlock`` lease)."""

    def __init__(self, worker: WorkerClient, session_id: int, block_id: int,
                 *, size_hint: int, tier: str = "", pinned: bool = False):
        super().__init__(block_id)
        self._worker = worker
        self._session = session_id
        self._pinned = pinned
        path = worker.create_local_block(session_id, block_id,
                                         size_hint=size_hint, tier=tier)
        self._f = open(path, "wb")
        self._closed = False

    def write(self, data: bytes) -> None:
        self._f.write(data)
        self.written += len(data)

    def close(self, cancel: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._worker.complete_local_block(self._session, self.block_id,
                                          cancel=cancel, pinned=self._pinned)


class GrpcBlockOutStream(BlockOutStream):
    """Remote write: chunks ride the client-stream as they are produced —
    a bounded queue feeds the in-flight RPC so network transfer overlaps
    the producer and peak memory stays ~queue-depth chunks, not a whole
    block (reference: ``GrpcDataWriter`` chunked flow control)."""

    _QUEUE_DEPTH = 4
    _CHUNK = 1 << 20

    def __init__(self, worker: WorkerClient, session_id: int, block_id: int,
                 *, tier: str = "", pinned: bool = False) -> None:
        super().__init__(block_id)
        self._worker = worker
        self._session = session_id
        self._tier = tier
        self._pinned = pinned
        self._queue: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._result: "futures.Future" = futures.Future()
        self._sender = threading.Thread(target=self._send, daemon=True,
                                        name=f"block-writer-{block_id}")
        self._sender.start()
        self._closed = False

    def _send(self) -> None:
        def gen():
            yield {"block_id": self.block_id, "session_id": self._session,
                   "tier": self._tier, "pinned": self._pinned}
            while True:
                item = self._queue.get()
                if item is None:
                    return
                yield {"data": item}

        try:
            resp = self._worker._channel.call_stream_in(
                self._worker.service, "write_block", gen())
            self._result.set_result(resp["length"])
        except BaseException as e:  # noqa: BLE001 - delivered on close()
            self._result.set_exception(e)
            # unblock a producer stuck on a full queue
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break

    def write(self, data: bytes) -> None:
        view = memoryview(data)
        for i in range(0, len(view), self._CHUNK):
            if self._result.done():  # sender died: surface its error
                self._result.result()
            self._queue.put(bytes(view[i:i + self._CHUNK]))
        self.written += len(data)

    def close(self, cancel: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        if cancel:
            # worker-side temp block is reaped by session cleanup; just
            # stop feeding and drop the RPC result
            try:
                self._result.result(timeout=30)
            except Exception:  # noqa: BLE001
                pass
            return
        n = self._result.result(timeout=300)
        if n != self.written:
            raise UnavailableError(
                f"short write: {n} of {self.written} bytes for block "
                f"{self.block_id}")
