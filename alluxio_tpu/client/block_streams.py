"""Per-block data streams: the transport decision ladder.

Re-design of ``core/client/fs/src/main/java/alluxio/client/block/stream/
{BlockInStream.java:97,LocalFileDataReader.java:41,GrpcDataReader.java:49,
LocalFileDataWriter,GrpcDataWriter}.java``:

Read ladder (closest wins):
1. **Short-circuit mmap** — block cached on a same-host worker: lease the
   file path (``open_local_block``) and mmap it. Zero RPC per byte, zero
   copy; the mmap'd buffer can be handed to ``jax.device_put`` directly.
2. **gRPC stream** — cached on a remote worker.
3. **UFS fallback through a worker** — not cached anywhere: a
   policy-chosen worker read-throughs from the UFS (caching it), client
   streams from that worker.

Write ladder mirrors it: short-circuit file write locally, gRPC stream
remotely.
"""

from __future__ import annotations

import mmap
import os
import queue
import socket
import threading
from concurrent import futures
from typing import Iterator, List, NamedTuple, Optional, Sequence

import numpy as np

from alluxio_tpu.client.remote_read import choose_route
from alluxio_tpu.rpc.clients import WorkerClient
from alluxio_tpu.utils.exceptions import UnavailableError
from alluxio_tpu.utils.wire import BlockInfo, WorkerNetAddress


#: cached ``metrics()`` accessor: the import machinery (sys.modules
#: lookup + attribute walk) was paid inside ``_record_read`` on EVERY
#: read — hot-path cost for a value that never changes. The function
#: (not the registry) is cached so ``reset_metrics()`` in tests still
#: takes effect.
_metrics_fn = None


def _metrics():
    global _metrics_fn
    if _metrics_fn is None:
        # deferred: alluxio_tpu.metrics imports are cyclic at module
        # load time (metrics sinks reach back into client config)
        from alluxio_tpu.metrics import metrics as fn

        _metrics_fn = fn
    return _metrics_fn()


def _record_read(bucket: str, nbytes: int) -> None:
    """Per-source read accounting: ``Client.BytesRead.<bucket>`` /
    ``Client.BlocksRead.<bucket>`` counters (additive — they roll up to
    ``Cluster.*`` on the metrics heartbeat)."""
    m = _metrics()
    m.counter(f"Client.BytesRead.{bucket}").inc(nbytes)
    m.counter(f"Client.BlocksRead.{bucket}").inc()


class BatchReadConf(NamedTuple):
    """Scatter/gather coalescing knobs (``atpu.user.batch.read.*``)."""

    enabled: bool = True
    max_op_bytes: int = 64 << 10
    max_ops: int = 256
    #: scatter read_many responses through the native plan executor
    #: (``atpu.user.native.fastpath.enabled``); pure-Python fallback is
    #: byte-identical
    native_fastpath: bool = True

    @classmethod
    def from_conf(cls, conf) -> "BatchReadConf":
        from alluxio_tpu.conf import Keys

        return cls(
            enabled=conf.get_bool(Keys.USER_BATCH_READ_ENABLED),
            max_op_bytes=conf.get_bytes(Keys.USER_BATCH_READ_MAX_OP_BYTES),
            max_ops=max(1, conf.get_int(Keys.USER_BATCH_READ_MAX_OPS)),
            native_fastpath=conf.get_bool(
                Keys.USER_NATIVE_FASTPATH_ENABLED))


def is_local_worker(address: WorkerNetAddress, local_hostname: str) -> bool:
    """Same-host check gate for the short-circuit path: the worker's shm
    dir must be a real local directory."""
    if address.host not in (local_hostname, "localhost", "127.0.0.1",
                            socket.gethostname()):
        return False
    return bool(address.shm_dir) and os.path.isdir(address.shm_dir)


class BlockInStream:
    """Positioned reads over one block."""

    def __init__(self, block_id: int, length: int) -> None:
        self.block_id = block_id
        self.length = length
        #: serving worker (set by BlockStoreClient); failed-worker retry
        #: marks it when a read dies mid-stream
        self.address = None
        #: raw serving source of the LAST read: a worker tier alias
        #: ("MEM"/"SSD"/...), "SHM" for short-circuit, or "UFS"
        self.last_source: Optional[str] = None

    def pread(self, offset: int, n: int) -> bytes:
        raise NotImplementedError

    def read_all(self) -> bytes:
        return self.pread(0, self.length)

    def pread_many(self, offsets: Sequence[int],
                   sizes: Sequence[int]) -> List[bytes]:
        """Scatter/gather: N positioned reads, results in request
        order. The base implementation is the per-op loop —
        byte-identical to calling :meth:`pread` N times; transports
        that can coalesce (``GrpcBlockInStream`` -> ``read_many`` RPC)
        override it."""
        return [self.pread(off, n) for off, n in zip(offsets, sizes)]

    def memoryview(self) -> Optional[memoryview]:
        """Zero-copy view when the source is local; None otherwise."""
        return None

    @property
    def source(self) -> str:
        raise NotImplementedError

    def source_bucket(self) -> str:
        """The last read's serving source, normalized to an input-doctor
        bucket: ``shm`` (same-host /dev/shm mmap), ``remote`` (cached on
        a remote worker, whatever its tier), ``ufs`` (cold
        read-through), or ``unknown``."""
        src = self.last_source
        if src is None:
            return "unknown"
        if src == "SHM":
            return "shm"
        if src == "UFS":
            return "ufs"
        return "remote"

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class LocalBlockInStream(BlockInStream):
    """Short-circuit: mmap the worker's block file via a path lease
    (reference: ``LocalFileDataReader.java:41``)."""

    source = "LOCAL"

    def __init__(self, worker: WorkerClient, session_id: int, block_id: int):
        lease = worker.open_local_block(session_id, block_id)
        super().__init__(block_id, lease["length"])
        self.last_source = "SHM"
        self._worker = worker
        self._session = session_id
        self._path = lease["path"]
        self._f = open(self._path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, prot=mmap.PROT_READ) \
            if lease["length"] > 0 else None

    def pread(self, offset: int, n: int) -> bytes:
        if self._mm is None:
            return b""
        out = self._mm[offset:offset + n]
        _record_read("shm", len(out))
        return out

    def memoryview(self) -> Optional[memoryview]:
        return memoryview(self._mm) if self._mm is not None else memoryview(b"")

    def numpy_view(self, dtype=np.uint8) -> np.ndarray:
        """Zero-copy ndarray over the mmap — feed straight to device_put."""
        if self._mm is None:
            return np.empty(0, dtype=dtype)
        _record_read("shm", len(self._mm))
        return np.frombuffer(self._mm, dtype=dtype)

    def close(self) -> None:
        if self._mm is not None:
            try:
                self._mm.close()
            except BufferError:
                # a numpy view is still live (e.g. in-flight device_put);
                # leave the mapping to GC — on Linux the pages stay valid
                # even if the file is later unlinked by eviction
                pass
            self._mm = None
        self._f.close()
        try:
            self._worker.close_local_block(self._session, self.block_id)
        except Exception:  # noqa: BLE001 - lease expires with session anyway
            pass


class GrpcBlockInStream(BlockInStream):
    """Remote read over gRPC chunk streams
    (reference: ``GrpcDataReader.java:49``).

    Reads larger than one stripe ride the parallel data plane
    (``client/remote_read.py``): concurrent range streams across the
    block's replica set — or pooled channels to a single worker — with
    hedged stragglers and zero-join ``memoryview`` assembly into one
    preallocated buffer. Smaller reads (and a runtime configured with
    ``stripe.size=0``) take the legacy single-stream loop, byte for
    byte what the seed shipped."""

    source = "REMOTE"

    def __init__(self, worker: WorkerClient, block_id: int, length: int,
                 *, ufs: Optional[dict] = None, cache: bool = True,
                 chunk_size: int = 1 << 20, remote_read=None,
                 replicas: Optional[list] = None, client_factory=None,
                 on_failed=None,
                 batch: Optional[BatchReadConf] = None) -> None:
        """``remote_read``: a ``RemoteReadRuntime`` (None = legacy only);
        ``replicas``: the block's location addresses, nearest first;
        ``client_factory``: address -> WorkerClient for replica fan-out;
        ``on_failed``: callback(address) when a worker dies mid-stripe
        (``BlockStoreClient.mark_failed`` plumbing);
        ``batch``: scatter/gather coalescing (None = per-op only)."""
        super().__init__(block_id, length)
        self._worker = worker
        self._ufs = ufs
        self._cache = cache
        self._chunk = chunk_size
        self._remote_read = remote_read
        self._replicas = replicas or []
        self._client_factory = client_factory
        self._on_failed = on_failed
        self._batch = batch

    # -- parallel data plane -------------------------------------------------
    def _striped_sources(self, conf):
        """Build the stripe fan-out: one source per replica (rotating
        onto pooled channels when concurrency exceeds the replica
        count), or ``concurrency`` pooled channels to the single
        serving worker."""
        from alluxio_tpu.client.remote_read import (
            MAX_POOLED_CHANNELS, GrpcReadSource,
        )

        addrs = [a for a in self._replicas if a is not None]
        if not addrs:
            if self.address is None:
                return []
            addrs = [self.address]
        fan_out = max(len(addrs), min(conf.concurrency,
                                      MAX_POOLED_CHANNELS * len(addrs)))
        sources = []
        for i in range(fan_out):
            addr = addrs[i % len(addrs)]
            channel = i // len(addrs)
            if self.address is not None and addr.key() == self.address.key():
                worker = self._worker
            elif self._client_factory is not None:
                worker = self._client_factory(addr)
            else:
                continue
            sources.append(GrpcReadSource(
                worker, addr, channel, block_id=self.block_id,
                ufs=self._ufs, cache=self._cache))
        return sources

    def _striped_read(self, offset: int, n: int):
        rt = self._remote_read
        read = rt.read(block_id=self.block_id,
                       sources=self._striped_sources(rt.conf),
                       offset=offset, length=n, chunk_size=self._chunk,
                       on_failed=self._on_failed)
        view = read.read_view()
        self.last_source = read.source_tag or "REMOTE"
        _record_read(self.source_bucket(), len(view))
        return view

    def _use_striped(self, n: int) -> bool:
        rt = self._remote_read
        return rt is not None and rt.enabled and \
            choose_route(n, striped=rt.conf) == "striped"

    def pread(self, offset: int, n: int) -> bytes:
        n = max(0, min(n, self.length - offset))
        if self._use_striped(n):
            return bytes(self._striped_read(offset, n))
        out = bytearray()
        source = None
        for msg in self._worker.read_block(
                self.block_id, offset=offset, length=n,
                chunk_size=self._chunk, ufs=self._ufs, cache=self._cache):
            out.extend(msg["data"])
            source = msg.get("source", source)
        # a pre-source-tagging worker sends no field: the read still
        # went to a remote worker's cache (cold reads raise without a
        # UFS descriptor, and with one the worker tags "UFS")
        self.last_source = source or "REMOTE"
        _record_read(self.source_bucket(), len(out))
        return bytes(out)

    def pread_many(self, offsets: Sequence[int],
                   sizes: Sequence[int]) -> List[bytes]:
        """Small-op batches coalesce into ``read_many`` RPCs: one wire
        round trip and ONE response buffer per ``max_ops`` ops instead
        of an RPC per op — the random-4k fix (docs/small_reads.md).
        Ineligible ops (too large, cold block needing a UFS descriptor,
        batching off) and any RPC failure take the per-op path, which
        is byte-identical by construction."""
        b = self._batch
        # choose_route decides per the routing matrix; the stream adds
        # its own constraint: cold blocks (UFS descriptor present) need
        # the read-through stream, so they stay per-op
        eligible = (self._ufs is None and len(sizes) > 0 and choose_route(
            max(sizes), batch=b, batch_ops=len(offsets)) == "batch")
        if not eligible:
            return super().pread_many(offsets, sizes)
        try:
            return self._batched_pread_many(offsets, sizes, b.max_ops)
        except Exception:  # noqa: BLE001 - transparent per-op fallback
            _metrics().counter("Client.BatchReadFallbacks").inc()
            return super().pread_many(offsets, sizes)

    def _batched_pread_many(self, offsets: Sequence[int],
                            sizes: Sequence[int],
                            max_ops: int) -> List[bytes]:
        import time as _time

        from alluxio_tpu.utils.tracing import current_span

        m = _metrics()
        sp = current_span()
        resps: List[dict] = []
        for i in range(0, len(offsets), max_ops):
            offs = list(offsets[i:i + max_ops])
            szs = [max(0, min(s, self.length - off))
                   for off, s in zip(offs, sizes[i:i + max_ops])]
            t0 = _time.perf_counter()
            resp = self._worker.read_many(self.block_id, offs, szs)
            if sp is not None:
                sp.phase("wire", (_time.perf_counter() - t0) * 1000.0)
            resps.append(resp)
            self.last_source = resp.get("source") or "REMOTE"
            m.counter("Client.BatchReadBatches").inc()
            m.counter("Client.BatchReadOps").inc(len(offs))
        out = self._scatter_responses(resps)
        total = sum(len(b) for b in out)
        m.counter("Client.BatchReadBytes").inc(total)
        _record_read(self.source_bucket(), total)
        return out

    def _scatter_responses(self, resps: List[dict]) -> List[bytes]:
        """Cut the collected ``read_many`` payloads into per-op bytes.
        With the fastpath on, all responses scatter into ONE dest
        buffer through a single GIL-free native call; the pure-Python
        slice loop below is the byte-identical fallback."""
        nops = sum(len(r["lengths"]) for r in resps)
        if self._batch is not None and self._batch.native_fastpath \
                and nops > 1:
            from alluxio_tpu.client import fastpath

            if fastpath.available():
                try:
                    return self._native_scatter(resps, nops)
                except fastpath.NativeExecError:
                    pass  # Client.NativeFallbacks already counted
            else:
                fastpath.note_unavailable()
        out: List[bytes] = []
        for resp in resps:
            buf = memoryview(resp["data"])
            pos = 0
            for n in resp["lengths"]:
                out.append(bytes(buf[pos:pos + n]))
                pos += n
        return out

    def _native_scatter(self, resps: List[dict], nops: int) -> List[bytes]:
        from alluxio_tpu import native
        from alluxio_tpu.client import fastpath

        lens = np.fromiter((n for r in resps for n in r["lengths"]),
                           dtype=np.int64, count=nops)
        bounds = np.zeros(nops + 1, dtype=np.int64)
        np.cumsum(lens, out=bounds[1:])
        ops = fastpath.op_table(nops)
        ops["len"] = lens  # kind zero-init == OP_COPY
        ops["dst_off"] = bounds[:-1]
        keep = []
        row = 0
        for resp in resps:
            k = len(resp["lengths"])
            loc = native._buffer_address(resp["data"])
            if loc is None:
                raise fastpath.NativeExecError("no payload address")
            addr, n, ka = loc
            keep.append(ka)
            ops["src"][row:row + k] = addr
            ops["src_len"][row:row + k] = n
            # offsets within this response = global dest offsets
            # rebased to the response's first op
            ops["src_off"][row:row + k] = \
                bounds[row:row + k] - bounds[row]
            row += k
        dest = bytearray(int(bounds[-1]))
        fastpath.execute_table(ops, dest, host="batch")
        del keep
        return fastpath.slice_out(dest, bounds.tolist())

    def read_all_view(self) -> memoryview:
        """The whole block as a buffer view: striped reads hand back
        their preallocated assembly buffer with NO final copy —
        ``numpy.frombuffer``/``jax.device_put`` consume it zero-copy.
        The legacy path wraps its joined bytes (one view, same data)."""
        if self._use_striped(self.length):
            return self._striped_read(0, self.length)
        return memoryview(self.pread(0, self.length))

    @property
    def is_ufs_fallback(self) -> bool:
        return self._ufs is not None


class BlockOutStream:
    def __init__(self, block_id: int) -> None:
        self.block_id = block_id
        self.written = 0

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def close(self, cancel: bool = False) -> None:
        raise NotImplementedError

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        self.close(cancel=exc_type is not None)
        return False


class LocalBlockOutStream(BlockOutStream):
    """Short-circuit write: append straight to the worker's temp file
    (reference: ``LocalFileDataWriter`` + ``CreateLocalBlock`` lease)."""

    def __init__(self, worker: WorkerClient, session_id: int, block_id: int,
                 *, size_hint: int, tier: str = "", pinned: bool = False):
        super().__init__(block_id)
        self._worker = worker
        self._session = session_id
        self._pinned = pinned
        path = worker.create_local_block(session_id, block_id,
                                         size_hint=size_hint, tier=tier)
        self._f = open(path, "wb")
        self._closed = False

    def write(self, data: bytes) -> None:
        self._f.write(data)
        self.written += len(data)

    def close(self, cancel: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._worker.complete_local_block(self._session, self.block_id,
                                          cancel=cancel, pinned=self._pinned)


class GrpcBlockOutStream(BlockOutStream):
    """Remote write: chunks ride the client-stream as they are produced —
    a bounded queue feeds the in-flight RPC so network transfer overlaps
    the producer and peak memory stays ~queue-depth chunks, not a whole
    block (reference: ``GrpcDataWriter`` chunked flow control)."""

    _QUEUE_DEPTH = 4
    _CHUNK = 1 << 20

    def __init__(self, worker: WorkerClient, session_id: int, block_id: int,
                 *, tier: str = "", pinned: bool = False,
                 chunk_size: Optional[int] = None) -> None:
        super().__init__(block_id)
        self._worker = worker
        self._session = session_id
        self._tier = tier
        self._pinned = pinned
        self._chunk = max(1, chunk_size) if chunk_size else self._CHUNK
        self._queue: "queue.Queue" = queue.Queue(maxsize=self._QUEUE_DEPTH)
        self._result: "futures.Future" = futures.Future()
        self._sender = threading.Thread(target=self._send, daemon=True,
                                        name=f"block-writer-{block_id}")
        self._sender.start()
        self._closed = False

    def _send(self) -> None:
        def gen():
            yield {"block_id": self.block_id, "session_id": self._session,
                   "tier": self._tier, "pinned": self._pinned}
            while True:
                item = self._queue.get()
                if item is None:
                    return
                yield {"data": item}

        try:
            resp = self._worker._channel.call_stream_in(
                self._worker.service, "write_block", gen())
            self._result.set_result(resp["length"])
        except BaseException as e:  # noqa: BLE001 - delivered on close()
            self._result.set_exception(e)
            # unblock a producer stuck on a full queue
            while True:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    break

    def write(self, data: bytes) -> None:
        view = memoryview(data)
        for i in range(0, len(view), self._chunk):
            if self._result.done():  # sender died: surface its error
                self._result.result()
            self._queue.put(bytes(view[i:i + self._chunk]))
        self.written += len(data)

    def close(self, cancel: bool = False) -> None:
        if self._closed:
            return
        self._closed = True
        self._queue.put(None)
        if cancel:
            # worker-side temp block is reaped by session cleanup; just
            # stop feeding and drop the RPC result
            try:
                self._result.result(timeout=30)
            except Exception:  # noqa: BLE001
                pass
            return
        n = self._result.result(timeout=300)
        if n != self.written:
            raise UnavailableError(
                f"short write: {n} of {self.written} bytes for block "
                f"{self.block_id}")
