"""Native fastpath: execute an assembled small-read plan outside the GIL.

``choose_route`` (``client/remote_read.py``) stays the planner; this
module is the bridge to the engine (``native/plan_exec.cpp``). A caller
packs its batch — SHM segment copies, ``read_many`` response scatter,
stripe commits — into ONE numpy op table (48-byte records mirroring
``struct AtpuPlanOp``), and :func:`execute_table` hands the whole table
across the ctypes boundary in a single call: ctypes drops the GIL for
the foreign call, so the entire batch runs at memcpy/pread speed with
zero per-op Python frames and exactly one GIL release/acquire.

Fallback contract (the route-ladder rule: the fastpath can only make
reads faster, never fail them): any native problem — library missing,
bounds rejection, I/O error, injected fault — surfaces as
:exc:`NativeExecError` after incrementing ``Client.NativeFallbacks``,
and the caller re-runs the same batch through its pure-Python path,
which is byte-identical by construction. Partial writes from a failed
native batch land in a buffer the caller then overwrites or discards.

Observability: ``Client.NativeBatches`` / ``Client.NativeBatchOps`` /
``Client.NativeBatchBytes`` count executed work, ``native_exec`` span
phase time feeds the read-path microscope, and the
``Client.NativeFallbacks`` rate (surfaced by ``fsadmin report
metrics``) makes a missing toolchain in prod loud, not silent.
Deterministic chaos rides ``atpu.debug.fault.native.exec.error.rate``:
a taken fault poisons ONE op mid-table, so the drill exercises a real
partial-write batch, not a clean pre-flight refusal.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence

from alluxio_tpu import native

OP_COPY = native.OP_COPY
OP_PREAD = native.OP_PREAD

#: direct stripe-chunk commits below this ride the plain memoryview
#: copy: a one-op table costs a few microseconds to build, which only
#: pays for itself once the GIL-free memcpy is big enough to matter
MIN_COPY_BYTES = 64 << 10

#: an op kind plan_exec.cpp does not know — the mid-table poison the
#: fault injector plants to drill genuine partial-write fallbacks
_POISON_KIND = 0xDEAD


class NativeExecError(Exception):
    """A native batch did not complete; the caller falls back to the
    byte-identical pure-Python path."""


def available() -> bool:
    """True when the compiled library is loadable (toolchain present
    and the build is current)."""
    return native.lib() is not None


def op_table(nops: int):
    """A zeroed op table ready for vectorized column fills."""
    import numpy as np

    return np.zeros(nops, dtype=native.op_dtype())


def _metrics():
    from alluxio_tpu.metrics import metrics

    return metrics()


def _maybe_poison(ops, host: str):
    """Fault hook: when ``atpu.debug.fault.native.exec.error.rate``
    takes this batch, poison one op in the MIDDLE of a copy of the
    table — the native executor writes everything before it, then
    rejects, so the fallback drill covers a genuinely partial buffer."""
    from alluxio_tpu.utils import faults

    if not faults.armed() or \
            not faults.injector().take_native_exec_error(host):
        return ops
    ops = ops.copy()
    ops["kind"][len(ops) // 2] = _POISON_KIND
    return ops


def execute_table(ops, dest, *, host: str = "") -> int:
    """Run a packed op table against ``dest`` in one GIL-free native
    call. Returns the bytes written; raises :exc:`NativeExecError`
    (after counting ``Client.NativeFallbacks``) when the library is
    unavailable or any op fails — the caller's Python path takes over.
    ``dest`` may hold partial results after a failure; the fallback
    overwrites every planned byte."""
    nops = len(ops)
    if nops == 0:
        return 0
    m = _metrics()
    ops = _maybe_poison(ops, host)
    t0 = time.perf_counter()
    rc = native.exec_plan(ops, dest)
    from alluxio_tpu.utils.tracing import current_span

    sp = current_span()
    if sp is not None:
        sp.phase("native_exec", (time.perf_counter() - t0) * 1000.0)
    if rc is None or rc < 0:
        m.counter("Client.NativeFallbacks").inc()
        raise NativeExecError(
            f"native plan exec failed (rc={rc}, ops={nops})")
    m.counter("Client.NativeBatches").inc()
    m.counter("Client.NativeBatchOps").inc(nops)
    m.counter("Client.NativeBatchBytes").inc(rc)
    return rc


def note_unavailable() -> None:
    """The conf asked for the fastpath but the library is missing:
    count a fallback so the condition shows up as a nonzero
    ``Client.NativeFallbacks`` rate in ``fsadmin report metrics``."""
    _metrics().counter("Client.NativeFallbacks").inc()


def slice_out(dest, bounds: Sequence[int]) -> List[bytes]:
    """Cut ``dest`` into per-op ``bytes`` at ``bounds`` (len N+1,
    monotone) — the List[bytes] surface ``pread_many`` promises."""
    mv = memoryview(dest)
    return [bytes(mv[a:b]) for a, b in zip(bounds, bounds[1:])]


def copy_into(dest, dst_off: int, src, *, host: str = "") -> bool:
    """One GIL-free memcpy of ``src`` into ``dest[dst_off:]`` — the
    stripe-commit form (multi-MB scratch buffers and direct chunks).
    True when the native path ran; False (library missing, no zero-copy
    address, injected fault, bounds rejection) means the caller does
    the plain Python copy — byte-identical either way."""
    handle = native.lib()
    if handle is None:
        return False
    loc = native._buffer_address(src)
    if loc is None:
        return False
    addr, n, keep = loc
    if n == 0:
        return True
    ops = op_table(1)
    ops[0] = (OP_COPY, -1, addr, 0, n, dst_off, n)
    try:
        execute_table(ops, dest, host=host)
    except NativeExecError:
        return False
    finally:
        del keep
    return True


class ReadPlan:
    """Incremental plan builder for mixed-source batches (striped
    scratch commits, tests). ``add_copy`` pins a zero-copy address of
    each source buffer; :meth:`execute` runs the packed table natively
    and :meth:`execute_python` is the byte-identical pure-Python
    reference the property tests (and the fallback contract) hold the
    native engine to."""

    __slots__ = ("_rows", "_keep")

    def __init__(self) -> None:
        #: (kind, fd, src_obj, src_addr, src_off, src_len, dst_off, len)
        self._rows: list = []
        self._keep: list = []

    def __len__(self) -> int:
        return len(self._rows)

    def add_copy(self, src, src_off: int, length: int,
                 dst_off: int) -> bool:
        """Plan ``dest[dst_off:dst_off+length] = src[src_off:...]``.
        False when ``src`` yields no zero-copy address (caller keeps
        that op on its Python path)."""
        loc = native._buffer_address(src)
        if loc is None:
            return False
        addr, n, keep = loc
        self._keep.append(keep)
        self._rows.append((OP_COPY, -1, src, addr, src_off, n,
                           dst_off, length))
        return True

    def add_pread(self, fd: int, file_off: int, length: int,
                  dst_off: int) -> None:
        """Plan ``dest[dst_off:dst_off+length] = pread(fd, file_off)``."""
        self._rows.append((OP_PREAD, fd, None, 0, file_off, 0,
                           dst_off, length))

    def table(self):
        ops = op_table(len(self._rows))
        for i, (kind, fd, _src, addr, soff, slen, doff, ln) in \
                enumerate(self._rows):
            ops[i] = (kind, fd, addr, soff, slen, doff, ln)
        return ops

    def execute(self, dest, *, host: str = "") -> int:
        return execute_table(self.table(), dest, host=host)

    def execute_python(self, dest) -> int:
        """The reference interpreter: identical semantics to
        ``atpu_plan_exec`` (same bounds checks, same in-order overlap
        resolution, same error positions), one Python frame per op."""
        import os

        mv = memoryview(dest).cast("B")
        total = 0
        for i, (kind, fd, src, _addr, soff, slen, doff, ln) in \
                enumerate(self._rows):
            if ln == 0:
                continue
            if doff > len(mv) or ln > len(mv) - doff:
                raise NativeExecError(f"python plan exec failed at op {i}")
            if kind == OP_COPY:
                if src is None or soff > slen or ln > slen - soff:
                    raise NativeExecError(
                        f"python plan exec failed at op {i}")
                smv = memoryview(src).cast("B")
                mv[doff:doff + ln] = smv[soff:soff + ln]
            elif kind == OP_PREAD:
                data = os.pread(fd, ln, soff)
                if len(data) != ln:
                    raise NativeExecError(
                        f"python plan exec failed at op {i}")
                mv[doff:doff + ln] = data
            else:
                raise NativeExecError(f"python plan exec failed at op {i}")
            total += ln
        return total
