"""fsspec adapter: the namespace for pandas/pyarrow/torch/dask users.

The non-JAX consumer surface (reference analogues: the HDFS-compat
client ``core/client/hdfs/.../AbstractFileSystem.java:80`` exposing
alluxio:// to Spark/Presto, and the S3 REST proxy
``proxy/s3/S3RestServiceHandler.java:75``): any library speaking fsspec
("atpu://path", or an ``AlluxioTpuFileSystem`` instance passed as
``filesystem=``) reads and writes through the caching data plane —
warm reads ride the short-circuit mmap path, writes honor the
configured write type.

Usage::

    import fsspec
    with fsspec.open("atpu:///data/f.parquet", master="host:port") as f:
        ...
    # or explicitly:
    afs = AlluxioTpuFileSystem(master="host:port")
    pq.read_table("/data/f.parquet", filesystem=afs)
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from fsspec import AbstractFileSystem
from fsspec.spec import AbstractBufferedFile

import contextlib

from alluxio_tpu.utils.exceptions import (
    DirectoryNotEmptyError, FileAlreadyExistsError,
    FileDoesNotExistError,
)


@contextlib.contextmanager
def _os_errors():
    """Translate framework errors into the OSError family fsspec
    consumers handle (`except FileNotFoundError/FileExistsError`)."""
    try:
        yield
    except FileDoesNotExistError as e:
        raise FileNotFoundError(str(e)) from e
    except FileAlreadyExistsError as e:
        raise FileExistsError(str(e)) from e
    except DirectoryNotEmptyError as e:
        raise OSError(str(e)) from e


def _entry(info) -> Dict[str, Any]:
    return {
        "name": info.path.lstrip("/"),
        "size": info.length,
        "type": "directory" if info.folder else "file",
        "mtime": info.last_modification_time_ms / 1000.0,
        "persisted": info.persisted,
        "in_memory_percentage": info.in_memory_percentage,
    }


class AlluxioTpuFile(AbstractBufferedFile):
    """Buffered file over FileInStream/FileOutStream."""

    def __init__(self, fs, path, mode="rb", write_type=None, **kwargs):
        self._write_type = write_type
        self._stream = None
        super().__init__(fs, path, mode, **kwargs)
        if mode == "rb":
            self._stream = fs._fs.open_file(path)

    # -- reads ---------------------------------------------------------------
    def _fetch_range(self, start: int, end: int) -> bytes:
        n = max(0, end - start)
        if n == 0:
            return b""
        return self._stream.pread(start, n)

    # -- writes --------------------------------------------------------------
    def _initiate_upload(self) -> None:
        kw = {"write_type": self._write_type} if self._write_type else {}
        # fsspec 'wb' contract: truncate existing files — the master
        # replaces the inode atomically under one lock (no
        # delete-then-create window losing data on a failed write)
        with _os_errors():
            self._stream = self.fs._fs.create_file(self.path,
                                                   overwrite=True, **kw)

    def _upload_chunk(self, final: bool = False) -> bool:
        self.buffer.seek(0)
        data = self.buffer.read()
        if data:
            self._stream.write(data)
        if final:
            self._stream.close()
            self._stream = None
        return True

    def close(self) -> None:
        super().close()
        if self._stream is not None:
            self._stream.close()
            self._stream = None


class AlluxioTpuFileSystem(AbstractFileSystem):
    """``atpu://`` filesystem over the FileSystem client."""

    protocol = ("atpu", "alluxio")
    root_marker = "/"
    #: no instance caching: a cached instance outlives close() (strong
    #: ref in the class cache -> callers get a closed filesystem back),
    #: and injected ``fs=`` kwargs tokenize via str() where CPython id
    #: reuse can collide across clusters. Construction cost is one
    #: client; owned clients are closed by the weakref finalizer.
    cachable = False

    def __init__(self, master: Optional[str] = None, *, fs=None,
                 conf=None, write_type: Optional[str] = None,
                 **kwargs) -> None:
        super().__init__(**kwargs)
        if fs is not None:
            self._fs = fs
            self._owns_fs = False
        else:
            from alluxio_tpu.client.file_system import FileSystem
            from alluxio_tpu.conf import Configuration, Keys

            conf = conf or Configuration()
            if master is None:
                master = (f"{conf.get(Keys.MASTER_HOSTNAME)}:"
                          f"{conf.get_int(Keys.MASTER_RPC_PORT)}")
            self._fs = FileSystem(master, conf=conf)
            self._owns_fs = True
            # fsspec never calls close() on registry-built instances
            # and caching is off: close the owned client (channels,
            # heartbeats) when the adapter is collected
            import weakref

            self._finalizer = weakref.finalize(self, self._fs.close)
        self._write_type = write_type

    @classmethod
    def _strip_protocol(cls, path: str) -> str:
        path = super()._strip_protocol(path)
        return path.lstrip("/") or ""

    def _norm(self, path: str) -> str:
        return "/" + self._strip_protocol(path)

    # -- metadata ------------------------------------------------------------
    def info(self, path, **kwargs) -> Dict[str, Any]:
        with _os_errors():
            return _entry(self._fs.get_status(self._norm(path)))

    def ls(self, path, detail=True, **kwargs) -> List:
        p = self._norm(path)
        with _os_errors():
            st = self._fs.get_status(p)
            if not st.folder:
                entries = [_entry(st)]
            else:
                entries = [_entry(i) for i in self._fs.list_status(p)]
        return entries if detail else [e["name"] for e in entries]

    def exists(self, path, **kwargs) -> bool:
        return self._fs.exists(self._norm(path))

    def created(self, path):
        import datetime

        with _os_errors():
            st = self._fs.get_status(self._norm(path))
        return datetime.datetime.fromtimestamp(
            st.creation_time_ms / 1000.0, tz=datetime.timezone.utc)

    def modified(self, path):
        import datetime

        with _os_errors():
            st = self._fs.get_status(self._norm(path))
        return datetime.datetime.fromtimestamp(
            st.last_modification_time_ms / 1000.0,
            tz=datetime.timezone.utc)

    # -- namespace ops -------------------------------------------------------
    def mkdir(self, path, create_parents=True, **kwargs) -> None:
        with _os_errors():
            self._fs.create_directory(self._norm(path),
                                      recursive=create_parents,
                                      allow_exists=False)

    def makedirs(self, path, exist_ok=False) -> None:
        with _os_errors():
            self._fs.create_directory(self._norm(path), recursive=True,
                                      allow_exists=exist_ok)

    def rmdir(self, path) -> None:
        with _os_errors():
            self._fs.delete(self._norm(path), recursive=False)

    def _rm(self, path) -> None:
        with _os_errors():
            self._fs.delete(self._norm(path), recursive=False)

    def rm(self, path, recursive=False, maxdepth=None):
        # fast path: recursive delete of one real dir is ONE master
        # RPC; glob/list inputs take the base implementation
        # (expand_path + per-file _rm)
        if isinstance(path, str) and recursive and maxdepth is None \
                and not self.has_glob(path):
            with _os_errors():
                self._fs.delete(self._norm(path), recursive=True)
            return
        return super().rm(path, recursive=recursive,
                          maxdepth=maxdepth)

    @staticmethod
    def has_glob(path: str) -> bool:
        return any(ch in path for ch in "*?[")

    def mv(self, path1, path2, **kwargs) -> None:
        with _os_errors():
            self._fs.rename(self._norm(path1), self._norm(path2))

    # -- data ----------------------------------------------------------------
    def _open(self, path, mode="rb", block_size=None, autocommit=True,
              cache_options=None, **kwargs):
        if mode not in ("rb", "wb"):
            raise NotImplementedError(f"mode {mode!r} (rb/wb only)")
        with _os_errors():
            return AlluxioTpuFile(self, self._norm(path), mode=mode,
                                  write_type=kwargs.pop("write_type",
                                                        self._write_type),
                                  block_size=block_size,
                                  cache_options=cache_options, **kwargs)

    def cat_file(self, path, start=None, end=None, **kwargs) -> bytes:
        p = self._norm(path)
        with _os_errors():
            if start is None and end is None:
                return self._fs.read_all(p)
            with self._fs.open_file(p) as f:
                length = f.length
                # fsspec contract: negative offsets are EOF-relative
                s = 0 if start is None else \
                    (start if start >= 0 else max(0, length + start))
                e = length if end is None else \
                    (end if end >= 0 else length + end)
                e = min(e, length)
                return f.pread(s, max(0, e - s))

    def pipe_file(self, path, value, **kwargs) -> None:
        wt = kwargs.pop("write_type", self._write_type)
        kw = {"write_type": wt} if wt else {}
        with _os_errors():
            self._fs.write_all(self._norm(path), value,
                               overwrite=True, **kw)

    def close(self) -> None:
        if self._owns_fs:
            self._finalizer()


def register() -> None:
    """Register ``atpu://`` / ``alluxio://`` with fsspec."""
    import fsspec

    for proto in AlluxioTpuFileSystem.protocol:
        fsspec.register_implementation(proto, AlluxioTpuFileSystem,
                                       clobber=True)
