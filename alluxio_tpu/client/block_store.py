"""Client-side block store: source selection + stream construction.

Re-design of ``core/client/fs/src/main/java/alluxio/client/block/
AlluxioBlockStore.java:63`` + the ladder in ``stream/BlockInStream.java:80-124``,
including the **passive cache trigger** (``AlluxioFileInStream.java:137``
triggerAsyncCaching): when a read was served remotely or from UFS, ask the
nearest local worker to cache the block in the background.
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional, Set

from alluxio_tpu.client.block_streams import (
    BatchReadConf, BlockInStream, BlockOutStream, GrpcBlockInStream,
    GrpcBlockOutStream, LocalBlockInStream, LocalBlockOutStream,
    is_local_worker,
)
from alluxio_tpu.client.policy import BlockLocationPolicy
from alluxio_tpu.client.remote_read import RemoteReadConf, RemoteReadRuntime
from alluxio_tpu.client.shm_transport import ShmTransport
from alluxio_tpu.rpc.clients import BlockMasterClient, WorkerClient
from alluxio_tpu.utils import ids as id_utils
from alluxio_tpu.utils.exceptions import UnavailableError
from alluxio_tpu.utils.retry import ExponentialTimeBoundedRetry
from alluxio_tpu.utils.wire import (
    BlockInfo, FileBlockInfo, FileInfo, TieredIdentity, WorkerInfo,
    WorkerNetAddress,
)


class BlockStoreClient:
    def __init__(self, block_master: BlockMasterClient, *,
                 identity: Optional[TieredIdentity] = None,
                 read_policy: Optional[BlockLocationPolicy] = None,
                 write_policy: Optional[BlockLocationPolicy] = None,
                 ufs_read_policy: Optional[BlockLocationPolicy] = None,
                 short_circuit: bool = True,
                 passive_cache: bool = True,
                 write_unavailable_window_s: float = 15.0,
                 streaming_chunk_size: int = 1 << 20,
                 streaming_writer_chunk_size: int = 1 << 20,
                 remote_read: Optional[RemoteReadConf] = None,
                 shm_enabled: bool = True,
                 shm_cache_max: int = 64,
                 shm_renew_fraction: float = 0.5,
                 batch_read: Optional[BatchReadConf] = None,
                 native_fastpath: bool = True) -> None:
        """``streaming_chunk_size``: per-message chunk of the gRPC read
        streams (``atpu.user.streaming.reader.chunk.size.bytes``);
        ``streaming_writer_chunk_size``: per-message chunk of the write
        stream (``atpu.user.streaming.writer.chunk.size.bytes``);
        ``remote_read``: striped-read tuning — the default conf stripes
        large remote reads, ``RemoteReadConf(stripe_size=0)`` pins the
        legacy single-stream path; ``shm_enabled`` /``shm_cache_max`` /
        ``shm_renew_fraction`` (``atpu.user.shm.*``): the same-host
        zero-copy SHM plane — disabled, step 1 of the ladder is the
        byte-identical short-circuit path; ``batch_read``
        (``atpu.user.batch.read.*``): scatter/gather coalescing for
        ``pread_many`` on remote streams; ``native_fastpath``
        (``atpu.user.native.fastpath.enabled``): execute assembled
        read plans in C++ with the GIL released — the SHM batch flag
        lives here, the batch/striped flags ride their confs."""
        self._bm = block_master
        self._identity = identity or TieredIdentity.from_spec(
            None, hostname=socket.gethostname())
        self._read_policy = read_policy or BlockLocationPolicy.create(
            "LOCAL_FIRST", identity=self._identity)
        self._write_policy = write_policy or BlockLocationPolicy.create(
            "LOCAL_FIRST", identity=self._identity)
        self._ufs_read_policy = ufs_read_policy or BlockLocationPolicy.create(
            "DETERMINISTIC_HASH", shards=1)
        self._short_circuit = short_circuit
        self._passive_cache = passive_cache
        self._write_unavailable_window_s = write_unavailable_window_s
        self._chunk_size = max(1, streaming_chunk_size)
        self._writer_chunk_size = max(1, streaming_writer_chunk_size)
        #: the parallel remote-read runtime every GrpcBlockInStream of
        #: this store shares: stripe executor + per-worker latency EWMAs
        #: (hedging learns across reads, so it lives here, not per-stream)
        self.remote_read = RemoteReadRuntime(remote_read)
        self.session_id = id_utils.create_session_id()
        #: same-host zero-copy plane (``atpu.user.shm.enabled``); None
        #: pins the legacy short-circuit path byte-for-byte
        self.shm: Optional[ShmTransport] = ShmTransport(
            self.session_id, cache_max=shm_cache_max,
            renew_fraction=shm_renew_fraction,
            host=socket.gethostname(),
            native_fastpath=native_fastpath) if shm_enabled else None
        #: scatter/gather coalescing conf shared by every remote stream
        self.batch_read = batch_read if batch_read is not None \
            else BatchReadConf()
        #: worker that served the most recent write (sync-persist targets it;
        #: LOCAL_FIRST keeps one file's blocks on one worker)
        self.last_write_worker: Optional[WorkerClient] = None
        self.last_write_address: Optional[WorkerNetAddress] = None
        self._workers: Dict[str, WorkerClient] = {}
        self._lock = threading.Lock()
        #: workers that recently failed reads, with the failure time —
        #: entries expire after _FAILED_WORKER_TTL_S so a recovered worker
        #: comes back into rotation (reference: AlluxioFileInStream
        #: failed-worker memory, :94-95)
        self._failed_workers: Dict[str, float] = {}

    @property
    def block_master(self):
        """The block-master client (public: placement reporting etc.)."""
        return self._bm

    # -- worker client cache -------------------------------------------------
    def worker_client(self, address: WorkerNetAddress) -> WorkerClient:
        key = f"{address.host}:{address.data_port or address.rpc_port}"
        with self._lock:
            c = self._workers.get(key)
            if c is None:
                c = WorkerClient(key)
                self._workers[key] = c
            return c

    _FAILED_WORKER_TTL_S = 30.0

    def _is_failed(self, key: str) -> bool:
        import time

        t = self._failed_workers.get(key)
        if t is None:
            return False
        if time.monotonic() - t > self._FAILED_WORKER_TTL_S:
            del self._failed_workers[key]
            return False
        return True

    def _live_workers(self) -> List[WorkerInfo]:
        return [w for w in self._bm.get_worker_infos()
                if not self._is_failed(w.address.key())]

    def mark_failed(self, address: Optional[WorkerNetAddress]) -> None:
        import time

        if address is not None:
            self._failed_workers[address.key()] = time.monotonic()

    # -- read ladder ---------------------------------------------------------
    def open_block(self, fbi: FileBlockInfo, *,
                   ufs_info: Optional[dict] = None,
                   cache_cold_reads: bool = True,
                   exclude: Optional[Set[str]] = None) -> BlockInStream:
        """Build the best stream for one block
        (reference: ``BlockInStream.create``, ``BlockInStream.java:97``).

        ``exclude``: worker address keys to skip for this call only (the
        caller saw a stale location there mid-retry)."""
        from alluxio_tpu.metrics import metrics

        info = fbi.block_info
        exclude = exclude or set()
        local_hostname = socket.gethostname()
        # 1) same-host cached copy: SHM zero-copy map first (one lease
        # RPC, then every read is a memoryview slice), then the legacy
        # path-lease short-circuit — each falls one rung on failure
        if self._short_circuit:
            for loc in info.locations:
                if loc.address.key() in exclude:
                    continue
                if is_local_worker(loc.address, local_hostname):
                    if self.shm is not None:
                        try:
                            stream = self.shm.open_stream(
                                self.worker_client(loc.address),
                                info.block_id)
                            stream.address = loc.address
                            metrics().counter(
                                "Client.BlockOpens.shm").inc()
                            return stream
                        except Exception:  # noqa: BLE001 - fall through ladder
                            # lease denied / block not in the top tier /
                            # map failed / worker dead (UnavailableError):
                            # the short-circuit and remote rungs still
                            # serve it
                            pass
                    try:
                        stream = LocalBlockInStream(
                            self.worker_client(loc.address), self.session_id,
                            info.block_id)
                        stream.address = loc.address
                        metrics().counter(
                            "Client.BlockOpens.shm").inc()
                        return stream
                    except Exception:  # noqa: BLE001 - fall through ladder
                        pass
        # 2) remote cached copy, nearest first; the UFS descriptor rides
        # along so a stale location (block evicted since the master's last
        # heartbeat) self-heals server-side via read-through
        if info.locations:
            addrs = [l.address for l in info.locations
                     if not self._is_failed(l.address.key())
                     and l.address.key() not in exclude]
            if addrs:
                idx = self._identity.nearest(
                    [a.tiered_identity for a in addrs])
                address = addrs[idx if idx is not None else 0]
                # the whole healthy replica set rides along, nearest
                # first: striped reads fan stripes out across it, and a
                # replica dying mid-read re-routes instead of failing
                replicas = [address] + [a for a in addrs
                                        if a.key() != address.key()]
                stream = GrpcBlockInStream(
                    self.worker_client(address), info.block_id, info.length,
                    ufs=ufs_info, cache=cache_cold_reads,
                    chunk_size=self._chunk_size,
                    remote_read=self.remote_read, replicas=replicas,
                    client_factory=self.worker_client,
                    on_failed=self.mark_failed, batch=self.batch_read)
                stream.address = address
                metrics().counter("Client.BlockOpens.remote").inc()
                self._maybe_passive_cache(info, ufs_info)
                return stream
        # 3) UFS fallback through a policy-chosen worker (caches read-through)
        if ufs_info is None:
            raise UnavailableError(
                f"block {info.block_id} has no cached copy and no UFS source")
        workers = [w for w in self._live_workers()
                   if w.address.key() not in exclude]
        address = self._ufs_read_policy.pick(workers, block_id=info.block_id,
                                             block_size=info.length)
        if address is None:
            raise UnavailableError("no live workers for UFS read")
        # striping still applies on the cold path: the stripes coalesce
        # into ONE worker-side UFS fetch (ufs_fetch.py registry) but
        # stream back over pooled channels
        stream = GrpcBlockInStream(self.worker_client(address),
                                   info.block_id, info.length, ufs=ufs_info,
                                   cache=cache_cold_reads,
                                   chunk_size=self._chunk_size,
                                   remote_read=self.remote_read,
                                   client_factory=self.worker_client,
                                   on_failed=self.mark_failed,
                                   batch=self.batch_read)
        stream.address = address
        metrics().counter("Client.BlockOpens.ufs").inc()
        return stream

    def _maybe_passive_cache(self, info: BlockInfo,
                             ufs_info: Optional[dict]) -> None:
        """Reading remotely: ask a local worker to cache a copy
        (reference: AsyncCache RPC, ``AlluxioFileInStream.java:137``)."""
        if not self._passive_cache or ufs_info is None:
            return
        local_hostname = socket.gethostname()
        for w in self._live_workers():
            if is_local_worker(w.address, local_hostname) and not any(
                    loc.address.key() == w.address.key()
                    for loc in info.locations):
                try:
                    self.worker_client(w.address).async_cache(
                        info.block_id, ufs_info["ufs_path"],
                        ufs_info["offset"], ufs_info["length"],
                        ufs_info.get("mount_id", 0))
                except Exception:  # noqa: BLE001 - best effort
                    pass
                return

    # -- write ---------------------------------------------------------------
    def _pick_writable(self, block_id: int, size_hint: int,
                       preferred: Optional[WorkerNetAddress]
                       ) -> Optional[WorkerNetAddress]:
        # Unfiltered list: the failed memory records READ errors (30s
        # TTL); a worker that botched one read is still a valid write
        # target, and filtering it here could starve the retry window.
        workers = list(self._bm.get_worker_infos())
        if preferred is not None and any(
                w.address.key() == preferred.key() for w in workers):
            # one file's blocks stay on one worker so worker-side persist
            # can stream them out locally (reference: LocalFirstPolicy
            # stickiness within a FileOutStream)
            return preferred
        return self._write_policy.pick(workers, block_id=block_id,
                                       block_size=size_hint)

    def open_block_writer(self, block_id: int, *, size_hint: int,
                          tier: str = "", pinned: bool = False,
                          preferred: Optional[WorkerNetAddress] = None
                          ) -> BlockOutStream:
        address = self._pick_writable(block_id, size_hint, preferred)
        if address is None and self._write_unavailable_window_s > 0:
            # Transient unavailability: a worker that missed heartbeats
            # under host overload is marked lost, empties the live set,
            # then re-registers seconds later. Wait out that window with
            # jittered backoff instead of failing the stream (reference:
            # client write retry on UnavailableException).
            policy = ExponentialTimeBoundedRetry(
                max_duration_s=self._write_unavailable_window_s,
                base_sleep_s=0.05, max_sleep_s=1.0)
            policy.attempt()  # first attempt already happened above
            while address is None and policy.attempt():
                address = self._pick_writable(block_id, size_hint, preferred)
        if address is None:
            raise UnavailableError("no live workers to write to")
        client = self.worker_client(address)
        self.last_write_worker = client
        self.last_write_address = address
        if self._short_circuit and is_local_worker(address,
                                                   socket.gethostname()):
            try:
                return LocalBlockOutStream(client, self.session_id, block_id,
                                           size_hint=size_hint, tier=tier,
                                           pinned=pinned)
            except Exception:  # noqa: BLE001
                pass
        return GrpcBlockOutStream(client, self.session_id, block_id,
                                  tier=tier, pinned=pinned,
                                  chunk_size=self._writer_chunk_size)

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        self.remote_read.close()
        if self.shm is not None:
            # unmap everything client-side; the cleanup_session calls
            # below release the leases gracefully on each worker
            # (worker-side close_session), TTL expiry backstops the rest
            self.shm.close()
        for c in self._workers.values():
            try:
                c.cleanup_session(self.session_id)
            except Exception:  # noqa: BLE001
                pass
