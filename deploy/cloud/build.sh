#!/usr/bin/env bash
# Build self-contained bootstrap artifacts for upload (reference:
# integration/{dataproc,emr}/build.sh): the per-cloud scripts source
# bootstrap-common.sh in the repo; cloud init actions download ONE
# file, so this inlines the common core between the >>> <<< sentinels.
set -eu
HERE="$(cd "$(dirname "$0")" && pwd)"
DEPLOY="$(dirname "${HERE}")"
DIST="${DEPLOY}/dist"
mkdir -p "${DIST}"

inline() {
  # $1: source script, $2: output
  awk -v common="${HERE}/bootstrap-common.sh" '
    /^# >>> bootstrap-common.sh/ {
      print "# ---- inlined deploy/cloud/bootstrap-common.sh ----";
      while ((getline line < common) > 0) print line;
      close(common); skipping = 1; next
    }
    /^# <<< bootstrap-common.sh/ { skipping = 0; next }
    !skipping { print }
  ' "$1" > "$2"
  chmod +x "$2"
  echo "built $2"
}

inline "${DEPLOY}/dataproc/alluxio-tpu-dataproc.sh" \
       "${DIST}/alluxio-tpu-dataproc.sh"
inline "${DEPLOY}/emr/alluxio-tpu-emr.sh" \
       "${DIST}/alluxio-tpu-emr.sh"
