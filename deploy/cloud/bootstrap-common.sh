#!/usr/bin/env bash
#
# Shared core for the cloud bootstrap actions (reference:
# integration/dataproc/alluxio-dataproc.sh + integration/emr/alluxio-emr.sh
# — behavior parity, own implementation): install the alluxio-tpu wheel,
# write site properties where the RUNTIME reads them
# (ATPU_SITE_PROPERTIES, default /etc/alluxio_tpu/site.properties), and
# start the role's processes via the wheel's `alluxio-tpu` console
# script. `deploy/cloud/build.sh` inlines this file into the per-cloud
# scripts so the uploaded artifact is self-contained (cloud init
# actions download exactly one file).
#
# Overridable for tests / air-gapped installs:
#   ATPU_SITE_PROPERTIES  site properties path (runtime contract,
#                         configuration.py reads this env var)
#   ATPU_WHEEL_URI        gs://, s3://, http(s):// or local wheel path
#                         (empty: pip install alluxio-tpu from the index)
#   ATPU_ROOT_UFS         root UFS uri (required on masters)
#   ATPU_PROPERTIES       semicolon-separated extra k=v site properties
#   ATPU_LOG_DIR          daemon log dir (default /var/log/alluxio-tpu)
#   ATPU_DRYRUN           1 = print the plan + write conf, never install
#                         or start processes (the test harness's mode)

set -eu

ATPU_SITE="${ATPU_SITE_PROPERTIES:-/etc/alluxio_tpu/site.properties}"
export ATPU_SITE_PROPERTIES="${ATPU_SITE}"
ATPU_LOG_DIR="${ATPU_LOG_DIR:-/var/log/alluxio-tpu}"
ATPU_DRYRUN="${ATPU_DRYRUN:-0}"

log() { echo "[alluxio-tpu-bootstrap] $*" >&2; }

run() {
  if [ "${ATPU_DRYRUN}" = "1" ]; then
    echo "PLAN: $*"
  else
    "$@"
  fi
}

run_daemon() {
  # $1: role subcommand of the `alluxio-tpu` console script
  if [ "${ATPU_DRYRUN}" = "1" ]; then
    echo "PLAN: daemon alluxio-tpu $1"
    return
  fi
  mkdir -p "${ATPU_LOG_DIR}"
  nohup alluxio-tpu "$1" > "${ATPU_LOG_DIR}/$1.out" 2>&1 &
  echo $! > "${ATPU_LOG_DIR}/$1.pid"
  log "started alluxio-tpu $1 (pid $(cat "${ATPU_LOG_DIR}/$1.pid"))"
}

append_site_property() {
  # keep the FIRST write of a key, matching the reference's
  # append_alluxio_property — operator-supplied extras are therefore
  # written BEFORE computed defaults so they win
  local property="$1" value="$2"
  if grep -qe "^\s*${property}=" "${ATPU_SITE}" 2>/dev/null; then
    log "property ${property} already set; keeping existing value"
  else
    echo "${property}=${value}" >> "${ATPU_SITE}"
  fi
}

write_site_properties() {
  # $1: master hostname
  mkdir -p "$(dirname "${ATPU_SITE}")"
  : > "${ATPU_SITE}"
  # operator extras FIRST: first-write-wins makes them authoritative
  local IFS=';'
  for kv in ${ATPU_PROPERTIES:-}; do
    [ -n "${kv}" ] || continue
    append_site_property "${kv%%=*}" "${kv#*=}"
  done
  unset IFS
  append_site_property "atpu.master.hostname" "$1"
  if [ -n "${ATPU_ROOT_UFS:-}" ]; then
    append_site_property "atpu.master.mount.table.root.ufs" \
      "${ATPU_ROOT_UFS}"
  fi
  # default worker MEM tier: half the host memory
  local mem_kb half_mb
  mem_kb="$(awk '/MemTotal/ {print $2}' /proc/meminfo)"
  half_mb="$((mem_kb / 2048))"
  append_site_property "atpu.worker.ramdisk.size" "${half_mb}MB"
  log "wrote $(wc -l < "${ATPU_SITE}") properties to ${ATPU_SITE}"
}

install_wheel() {
  case "${ATPU_WHEEL_URI:-}" in
    "")      run pip install alluxio-tpu ;;
    gs://*)  run gsutil cp "${ATPU_WHEEL_URI}" /tmp/alluxio_tpu.whl
             run pip install /tmp/alluxio_tpu.whl ;;
    s3://*)  run aws s3 cp "${ATPU_WHEEL_URI}" /tmp/alluxio_tpu.whl
             run pip install /tmp/alluxio_tpu.whl ;;
    http*)   run curl -fsSL -o /tmp/alluxio_tpu.whl "${ATPU_WHEEL_URI}"
             run pip install /tmp/alluxio_tpu.whl ;;
    *)       run pip install "${ATPU_WHEEL_URI}" ;;
  esac
}

start_role() {
  # $1: role (master|worker)
  case "$1" in
    master)
      run alluxio-tpu format
      run_daemon master
      run_daemon job-master
      ;;
    worker)
      run_daemon worker
      run_daemon job-worker
      ;;
    *) log "unknown role '$1'"; exit 2 ;;
  esac
}

bootstrap() {
  # $1: master hostname; $2: role
  if [ -z "$1" ]; then
    log "FATAL: could not determine the master hostname"
    exit 2
  fi
  log "bootstrapping role=$2 master=$1 (dryrun=${ATPU_DRYRUN})"
  install_wheel
  write_site_properties "$1"
  start_role "$2"
  log "bootstrap complete"
}
