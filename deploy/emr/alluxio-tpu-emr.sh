#!/usr/bin/env bash
#
# EMR bootstrap action (reference: integration/emr/alluxio-emr.sh —
# same job, own script):
#
# Upload the BUILT artifact (deploy/cloud/build.sh inlines the common
# core so the uploaded file is self-contained):
#
#   bash deploy/cloud/build.sh
#   aws s3 cp deploy/dist/alluxio-tpu-emr.sh s3://<bucket>/
#   aws emr create-cluster ... \
#     --bootstrap-actions Path=s3://<bucket>/alluxio-tpu-emr.sh,\
#       Args=[s3://my-bucket/warehouse,s3://my-bucket/alluxio_tpu.whl]
#
#   $1: root UFS uri (optional)
#   $2: wheel uri (optional)
#   $3: extra site properties "k=v;k2=v2" (optional)
#
# EMR's instance.json distinguishes master from core/task nodes; the
# master's private DNS comes from job-flow.json. Both paths honor env
# overrides for tests (ATPU_EMR_IS_MASTER / ATPU_EMR_MASTER_HOST).

set -eu

# >>> bootstrap-common.sh (replaced inline by deploy/cloud/build.sh) >>>
HERE="$(cd "$(dirname "$0")" && pwd)"
. "${HERE}/../cloud/bootstrap-common.sh"
# <<< bootstrap-common.sh <<<

ATPU_ROOT_UFS="${ATPU_ROOT_UFS:-${1:-}}"
ATPU_WHEEL_URI="${ATPU_WHEEL_URI:-${2:-}}"
ATPU_PROPERTIES="${ATPU_PROPERTIES:-${3:-}}"
export ATPU_ROOT_UFS ATPU_WHEEL_URI ATPU_PROPERTIES

is_master() {
  if [ -n "${ATPU_EMR_IS_MASTER:-}" ]; then
    [ "${ATPU_EMR_IS_MASTER}" = "true" ]
  else
    grep -q '"isMaster"[[:space:]]*:[[:space:]]*true' \
      /mnt/var/lib/info/instance.json
  fi
}

master_host() {
  if [ -n "${ATPU_EMR_MASTER_HOST:-}" ]; then
    echo "${ATPU_EMR_MASTER_HOST}"
  else
    # masterPrivateDnsName in job-flow.json
    sed -n 's/.*"masterPrivateDnsName"[[:space:]]*:[[:space:]]*"\([^"]*\)".*/\1/p' \
      /mnt/var/lib/info/job-flow.json | head -1
  fi
}

if is_master; then
  bootstrap "$(hostname -f)" master
else
  MH="$(master_host)"
  if [ -z "${MH}" ]; then
    echo "[alluxio-tpu-bootstrap] FATAL: no masterPrivateDnsName in" \
         "job-flow.json — refusing to start a worker at localhost" >&2
    exit 2
  fi
  bootstrap "${MH}" worker
fi
