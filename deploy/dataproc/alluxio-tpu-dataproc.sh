#!/usr/bin/env bash
#
# Dataproc initialization action (reference:
# integration/dataproc/alluxio-dataproc.sh — same job, own script):
#
# Upload the BUILT artifact (deploy/cloud/build.sh inlines the common
# core so the uploaded file is self-contained — init actions download
# exactly one file):
#
#   bash deploy/cloud/build.sh
#   gsutil cp deploy/dist/alluxio-tpu-dataproc.sh gs://<bucket>/
#   gcloud dataproc clusters create my-cluster \
#     --initialization-actions gs://<bucket>/alluxio-tpu-dataproc.sh \
#     --metadata atpu_root_ufs=gs://my-bucket/warehouse \
#     --metadata atpu_wheel_uri=gs://my-bucket/alluxio_tpu.whl \
#     --metadata atpu_site_properties='atpu.worker.ramdisk.size=32GB'
#
# Role + master come from the Dataproc VM metadata server; every knob
# can be overridden by env for tests (see bootstrap-common.sh).

set -eu

# >>> bootstrap-common.sh (replaced inline by deploy/cloud/build.sh) >>>
HERE="$(cd "$(dirname "$0")" && pwd)"
. "${HERE}/../cloud/bootstrap-common.sh"
# <<< bootstrap-common.sh <<<

metadata() {
  # $1: key, $2: default; env override ATPU_MD_<KEY> wins (tests)
  local env_key="ATPU_MD_$(echo "$1" | tr 'a-z-' 'A-Z_')"
  local override
  override="$(eval "echo \"\${${env_key}:-}\"")"
  if [ -n "${override}" ]; then
    echo "${override}"
  elif [ -x /usr/share/google/get_metadata_value ]; then
    /usr/share/google/get_metadata_value "attributes/$1" || echo "$2"
  else
    echo "$2"
  fi
}

ROLE_RAW="$(metadata dataproc-role Worker)"
MASTER="$(metadata dataproc-master localhost)"
ATPU_ROOT_UFS="${ATPU_ROOT_UFS:-$(metadata atpu_root_ufs "")}"
ATPU_WHEEL_URI="${ATPU_WHEEL_URI:-$(metadata atpu_wheel_uri "")}"
ATPU_PROPERTIES="${ATPU_PROPERTIES:-$(metadata atpu_site_properties "")}"
export ATPU_ROOT_UFS ATPU_WHEEL_URI ATPU_PROPERTIES

case "${ROLE_RAW}" in
  Master) ROLE=master ;;
  *)      ROLE=worker ;;
esac

bootstrap "${MASTER}" "${ROLE}"
