#!/bin/bash
# Opportunistic TPU perf harvest (round-4 verdict #1): the axon tunnel
# grants a device intermittently, so probe cheaply in a loop and run the
# full bench only when a grant is live. Never kills a granted process.
cd /root/repo
for i in $(seq 1 "${HARVEST_TRIES:-40}"); do
  echo "[harvest] probe $i $(date -u +%H:%M:%S)" >&2
  if timeout 180 python -c 'import jax, jax.numpy as jnp; d=jax.devices()[0]; jnp.ones((4,)).sum().block_until_ready(); print("PROBE_OK", d)' 2>/dev/null | grep -q PROBE_OK; then
    echo "[harvest] grant live — running full bench" >&2
    BENCH_PROBE_TIMEOUT_S=170 python bench.py > /tmp/bench_harvest.json 2>/tmp/bench_harvest.log
    rc=$?
    echo "[harvest] bench rc=$rc" >&2
    if [ $rc -eq 0 ] && grep -q '"vs_baseline"' /tmp/bench_harvest.json && ! grep -q tpu_wedged /tmp/bench_harvest.json; then
      cp /tmp/bench_harvest.json BENCH_HEADLINE_r5.json
      echo "[harvest] SUCCESS — BENCH_HEADLINE_r5.json copied (bench.py writes BENCH_TPU.json itself when configs run)" >&2
      exit 0
    fi
  fi
  sleep "${HARVEST_SLEEP_S:-600}"
done
echo "[harvest] no grant landed" >&2
exit 3
