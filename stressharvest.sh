#!/bin/bash
# Opportunistic TPU perf harvest (round-4 verdict #1): the axon tunnel
# grants a device intermittently, so probe cheaply in a loop and run the
# full bench only when a grant is live. Never kills a granted process.
# On success the evidence is committed IMMEDIATELY — a grant can land
# minutes before round end and uncommitted artifacts would be lost to
# the next builder.
cd /root/repo
for i in $(seq 1 "${HARVEST_TRIES:-40}"); do
  echo "[harvest] probe $i $(date -u +%H:%M:%S)" >&2
  if timeout 180 python -c 'import jax, jax.numpy as jnp; d=jax.devices()[0]; jnp.ones((4,)).sum().block_until_ready(); print("PROBE_OK", d)' 2>/dev/null | grep -q PROBE_OK; then
    echo "[harvest] grant live — running full bench" >&2
    BENCH_PROBE_TIMEOUT_S=170 python bench.py > /tmp/bench_harvest.json 2>/tmp/bench_harvest.log
    rc=$?
    echo "[harvest] bench rc=$rc" >&2
    # preserve the run's full stderr next to the earlier device logs,
    # numbered after the existing r05_device_run* files
    n=$(ls bench_logs/ 2>/dev/null | grep -c "r05_device_run")
    run_log="bench_logs/r05_device_run$((n + 1)).txt"
    if grep -q "warm HBM-tier read epochs" /tmp/bench_harvest.log; then
      cp /tmp/bench_harvest.log "$run_log"
    fi
    if [ $rc -eq 0 ] && grep -q '"vs_baseline"' /tmp/bench_harvest.json && ! grep -q tpu_wedged /tmp/bench_harvest.json; then
      cp /tmp/bench_harvest.json BENCH_HEADLINE_r5.json
      git add BENCH_HEADLINE_r5.json bench_logs/ BENCH_TPU.json 2>/dev/null
      git commit -m "Harvest on-device bench evidence: headline + TPU config rows

No-Verification-Needed: bench-artifact snapshot, no source change" >&2
      echo "[harvest] SUCCESS — device evidence committed" >&2
      exit 0
    fi
    # partial evidence (e.g. headline epochs ran, then a later stage
    # died): still commit the raw log so the device numbers survive
    if [ -f "$run_log" ]; then
      git add "$run_log"
      git commit -m "Preserve partial on-device bench log (run died before completing)

No-Verification-Needed: bench-artifact snapshot, no source change" >&2
      echo "[harvest] partial evidence committed ($run_log)" >&2
    fi
  fi
  sleep "${HARVEST_SLEEP_S:-600}"
done
echo "[harvest] no grant landed" >&2
exit 3
